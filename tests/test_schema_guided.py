"""Schema-constrained guided decoding (SURVEY.md §7 hard part 2).

The reference validates structured LLM output post-hoc with zod
(``src/agent/llm-parser.ts:21-210``); serving in-tree lets us constrain
generation itself. These tests check both directions:

- the compiled automata *accept* exactly the documents the pydantic models
  validate (round-trip + rejection cases), and
- a random-weights model forced through the mask *always* produces output
  that strictly parses into each dataclass (the VERDICT r1 done-criterion).
"""

import json

import numpy as np
import pytest

from runbookai_tpu.agent import llm_parser as lp
from runbookai_tpu.engine.request import EngineRequest, SamplingParams
from runbookai_tpu.model.guided import JsonMaskProvider
from runbookai_tpu.model.schema_guided import (
    SchemaLimits,
    SchemaMachine,
    compile_model,
    orchestrator_schemas,
)
from runbookai_tpu.utils.tokens import ByteTokenizer

MODELS = {
    "triage": lp.TriageResult,
    "hypotheses": lp.HypothesisGeneration,
    "evaluation": lp.EvidenceEvaluation,
    "conclusion": lp.Conclusion,
    "remediation": lp.RemediationPlan,
    "log_analysis": lp.LogAnalysis,
}

SAMPLES = {
    "triage": lp.TriageResult(
        severity="critical", summary="db down", affected_services=["api", "db"],
        symptoms=["5xx spike"], signals=["OOM at 12:01"]),
    "hypotheses": lp.HypothesisGeneration(hypotheses=[
        lp.GeneratedHypothesis(statement="conn pool exhausted", priority=0.9,
                               rationale="errors mention timeouts")]),
    "evaluation": lp.EvidenceEvaluation(
        action="branch", confidence=0.7, reasoning="split by region",
        supports=True, strength="strong",
        sub_hypotheses=[lp.GeneratedHypothesis(statement="us-east only",
                                               priority=0.8)]),
    "conclusion": lp.Conclusion(
        root_cause="bad deploy", confidence="high", affected_services=["api"],
        contributing_factors=["no canary"], summary="Rollback fixed it."),
    "remediation": lp.RemediationPlan(
        steps=[lp.PlannedRemediationStep(
            description="rollback", action="skill:rollback-deployment",
            params={"service": "api", "revision": 3}, risk="high",
            requires_approval=True)],
        rollback="redeploy v2", notes="watch error rate"),
    "log_analysis": lp.LogAnalysis(
        error_categories=["timeout"], services_mentioned=["api"],
        notable_lines=["ERROR conn refused"],
        suggested_hypotheses=[lp.GeneratedHypothesis(statement="net split",
                                                     priority=0.4)]),
}


def _machine(name: str, **lim) -> SchemaMachine:
    return SchemaMachine(compile_model(MODELS[name]), name,
                         limits=SchemaLimits(**lim) if lim else None)


# ------------------------------------------------------------------ accept


@pytest.mark.parametrize("name", sorted(MODELS))
def test_accepts_canonical_serialization(name):
    """model_dump_json is exactly the canonical emission order the grammar
    forces, so every pydantic round-trip must be accepted byte-for-byte."""
    doc = SAMPLES[name].model_dump_json().encode()
    m = _machine(name)
    assert m.advance_bytes(doc), f"died at prefix {doc!r}"
    assert m.is_complete


def test_accepts_whitespace_and_unicode():
    doc = ('{ "severity" : "low" ,\n "summary" : "café \\n down ✓" ,'
           ' "affected_services" : [ ] , "symptoms" : [ "a" , "b" ] ,'
           ' "signals" : [ ] }').encode()
    m = _machine("triage")
    assert m.advance_bytes(doc) and m.is_complete
    parsed = lp.TriageResult.model_validate(json.loads(doc))
    assert parsed.symptoms == ["a", "b"]


def test_accepts_number_variants():
    for num in ("0", "0.5", "-1.25", "1e3", "-2.5E-2", "10"):
        doc = ('{"hypotheses":[{"statement":"x","priority":%s,'
               '"rationale":""}]}' % num).encode()
        m = _machine("hypotheses")
        assert m.advance_bytes(doc) and m.is_complete, num
        json.loads(doc)  # grammar and json agree


# ------------------------------------------------------------------ reject


@pytest.mark.parametrize("doc", [
    # enum violation: severity must be critical|high|medium|low
    b'{"severity":"urgent"',
    # wrong first key (fixed emission order)
    b'{"summary":',
    # skipping a required key: severity must be followed by summary
    b'{"severity":"low","symptoms"',
    # leading zero (json.loads rejects 01)
    b'{"severity":"low","summary":"s","affected_services":[],'
    b'"symptoms":[],"signals":01',
    # bad escape
    b'{"severity":"low","summary":"\\x',
    # closing the object before all fields are emitted
    b'{"severity":"low","summary":"s"}',
])
def test_rejects_schema_violations(doc):
    m = _machine("triage")
    ok = m.advance_bytes(doc)
    assert not ok and m.dead


def test_rejects_trailing_garbage_and_dangling_exponent():
    m = _machine("hypotheses")
    assert not m.advance_bytes(
        b'{"hypotheses":[{"statement":"x","priority":1e,')
    full = SAMPLES["triage"].model_dump_json().encode()
    m = _machine("triage")
    assert m.advance_bytes(full) and m.is_complete
    assert m.advance(ord(" "))  # trailing whitespace ok
    assert not m.advance(ord("x"))  # trailing garbage dies


def test_string_length_cap_forces_close():
    m = _machine("triage", max_str_len=4, max_array_items=2)
    assert m.advance_bytes(b'{"severity":"low","summary":"abcd')
    assert not m.copy().advance(ord("e"))  # at cap: content refused
    assert m.advance(ord('"'))  # close accepted


def test_array_item_cap_blocks_comma():
    m = _machine("triage", max_str_len=64, max_array_items=2)
    assert m.advance_bytes(
        b'{"severity":"low","summary":"s","affected_services":["a","b"')
    assert not m.copy().advance(ord(","))  # third item refused
    assert m.advance(ord("]"))


# ---------------------------------------------------- masked random decode


def _random_generate(name: str, seed: int, max_steps: int = 4000) -> str:
    """Uniform sampling over the allowed-token mask — a model with zero
    knowledge of JSON. Termination is steered purely by the grammar."""
    tok = ByteTokenizer()
    provider = JsonMaskProvider(tok, schemas=orchestrator_schemas(),
                                limits=SchemaLimits(max_str_len=8,
                                                    max_array_items=2))
    req = EngineRequest(prompt_ids=[],
                        sampling=SamplingParams(guided=name))
    rng = np.random.RandomState(seed)
    out = bytearray()
    for _ in range(max_steps):
        mask = provider.mask(req)
        allowed = np.flatnonzero(mask)
        t = int(rng.choice(allowed))
        if t in (tok.eot_id, tok.eos_id):
            assert provider.machine_for(req).is_complete
            return out.decode("utf-8")
        provider.advance(req, t)
        out += tok.id_to_bytes(t)
    raise AssertionError(f"no completion within {max_steps} steps: {out[:200]}")


@pytest.mark.parametrize("name", sorted(MODELS))
def test_random_masked_decode_always_validates(name):
    """VERDICT r1 #5 done-criterion: random weights forced through the mask
    always parse into the dataclass — strict json.loads + model_validate,
    no tolerant fallback."""
    for seed in (0, 1, 2):
        text = _random_generate(name, seed)
        payload = json.loads(text)  # strict: must be valid JSON
        MODELS[name].model_validate(payload)  # strict: must match the schema


def test_generic_json_grammar_still_available():
    tok = ByteTokenizer()
    provider = JsonMaskProvider(tok, schemas=orchestrator_schemas())
    req = EngineRequest(prompt_ids=[], sampling=SamplingParams(guided="json"))
    machine = provider.machine_for(req)
    from runbookai_tpu.model.guided import JsonMachine

    assert isinstance(machine, JsonMachine)


# ------------------------------------------------------------------ engine


@pytest.mark.parametrize("name", ["conclusion", "evaluation"])
def test_engine_end_to_end_schema_decode(name):
    """Random-weights engine + temperature 1.0: the decoded text strictly
    parses into the schema's dataclass (guided masks steer everything)."""
    import asyncio

    from runbookai_tpu.model.jax_tpu import JaxTpuClient

    client = JaxTpuClient.for_testing(
        temperature=1.0, max_new_tokens=280, max_seq_len=512,
        schema_limits=SchemaLimits(max_str_len=6, max_array_items=1))

    async def run():
        try:
            return await client.complete("Investigate the outage.",
                                         schema=name)
        finally:
            await client.shutdown()

    text = asyncio.run(run())
    payload = json.loads(text)
    MODELS[name].model_validate(payload)


def test_orchestrator_requests_schemas():
    """The orchestrator passes grammar names through the seam; clients
    without schema support (mocks) still work via the fallback."""
    import asyncio

    from runbookai_tpu.agent.orchestrator import (
        InvestigationOrchestrator,
        ToolExecutor,
    )

    seen: list = []

    class SchemaAwareMock:
        async def complete(self, prompt, schema=None):
            seen.append(schema)
            return "{}"

    orch = InvestigationOrchestrator(SchemaAwareMock(), ToolExecutor({}))
    asyncio.run(orch.investigate("INC-1", "api is down"))
    assert "triage" in seen and "hypotheses" in seen and "conclusion" in seen

    class PlainMock:
        async def complete(self, prompt):
            return "{}"

    orch = InvestigationOrchestrator(PlainMock(), ToolExecutor({}))
    res = asyncio.run(orch.investigate("INC-2", "api is down"))
    assert res.summary is not None


def test_grammar_admits_pydantic_invalid_numbers():
    """Documented degradation (ADVICE r2): numeric range constraints are NOT
    in the byte grammar — a confidence of 7.5 passes the automaton, and the
    tolerant parser downstream is the layer that handles it."""
    doc = (b'{"action":"confirm","confidence":7.5,"reasoning":"r",'
           b'"supports":true,"strength":"strong","sub_hypotheses":[]}')
    m = _machine("evaluation")
    assert m.advance_bytes(doc) and m.is_complete  # grammar-valid

    from runbookai_tpu.agent import llm_parser as lp

    parsed = lp.parse_evaluation(doc.decode())
    # The fallback layer must yield a *usable* evaluation object, not raise.
    assert parsed.action in ("continue", "branch", "prune", "confirm")
    assert isinstance(parsed.confidence, float)


@pytest.mark.parametrize("schema_name", ["triage", "hypotheses", "evaluation",
                                         "conclusion", "remediation",
                                         "log_analysis"])
async def test_every_schema_parses_across_sampling_regimes(schema_name):
    """Fuzz the flagship guarantee: for EVERY orchestrator grammar, across
    greedy and high-temperature sampling, a random-weights model emits
    strictly parseable JSON with the schema's top-level keys present."""
    import json as _json

    from runbookai_tpu.model.jax_tpu import JaxTpuClient
    from runbookai_tpu.model.schema_guided import (
        SchemaLimits,
        orchestrator_schemas,
    )

    # Tight generation bounds make the bounded document provably fit the
    # token budget — without them an unbounded-ish 512-byte-string field
    # can absorb any budget at high temperature (the documented
    # truncation caveat, not a grammar failure).
    client = JaxTpuClient.for_testing(
        max_new_tokens=1500, max_seq_len=4096, num_pages=1024,
        schema_limits=SchemaLimits(max_str_len=16, max_array_items=2,
                                   max_any_bytes=96))
    try:
        for temp in (0.0, 1.2):
            client.temperature = temp
            text = await client.complete(
                f"Produce the {schema_name} document.", schema=schema_name)
            doc = _json.loads(text)  # must parse strictly, every time
            assert isinstance(doc, dict) and doc, (schema_name, temp, text)
            # Forced key order: EVERY schema field must be present.
            schema = orchestrator_schemas()[schema_name]
            want_keys = {k.decode().strip('"') for k, _ in schema.fields}
            assert set(doc) == want_keys, (schema_name, temp, set(doc))
    finally:
        await client.shutdown()


# --------------------------------------------------------------------- #
# wrap-up budget hardening (advisor r3 findings)                        #
# --------------------------------------------------------------------- #


def test_wrapup_budget_not_escapable_via_number_comma():
    """A ',' terminating a number is re-interpreted in AFTER mode; the redo
    path must not bypass the wrap-up check — otherwise ',0' (arrays) and
    ',"":0' (objects) cycles grow the document forever past the budget."""
    from runbookai_tpu.model.guided import JsonMachine

    m = JsonMachine(budget=4)
    for b in b"[1,2":
        assert m.advance(b)
    assert m.budget <= 0
    # The escape: keep appending ',0' — must die, not run forever.
    c = m.copy()
    for _ in range(8):
        if not (c.advance(ord(",")) and c.advance(ord("0"))):
            break
    else:
        raise AssertionError("unbounded ',0' cycle survived wrap-up")
    # No deadlock: the close is still admissible and completes the doc.
    c2 = m.copy()
    assert c2.advance(ord("]")) and c2.is_complete

    m2 = JsonMachine(budget=6)
    for b in b'{"a":1':
        assert m2.advance(b)
    assert m2.budget <= 0
    c3 = m2.copy()
    assert not (c3.advance(ord(",")) and c3.advance(ord('"')))
    c4 = m2.copy()
    assert c4.advance(ord("}")) and c4.is_complete


def test_budget_bucket_sized_from_vocab_longest_token():
    """Masks cached at one budget head-room must not be reused where a
    longer-than-bucket token could cross the wrap-up boundary mid-token:
    the bucket tracks the measured longest token, not a hard-coded 32."""
    from runbookai_tpu.model.guided import JsonMachine

    m = JsonMachine(budget=100, budget_bucket=64)
    # STRICTLY greater than the longest token: at budget == longest-token a
    # token whose final byte is re-interpreted (number ',') sees the
    # post-decrement budget hit 0 and diverges from budget > bucket.
    assert m.budget_bucket == 65
    assert m.copy().budget_bucket == 65  # survives copy
    # Distinct budgets below the bucket hash to distinct signatures.
    a = JsonMachine(budget=40, budget_bucket=64).signature()
    b = JsonMachine(budget=50, budget_bucket=64).signature()
    assert a != b
    # budget == longest token and budget > bucket genuinely diverge on a
    # longest-token whose final byte terminates a number: b'1'*63 + b','
    # is refused at budget 64 (redo sees 0 head-room, AFTER-mode wrap-up
    # rejects ',') but admitted at budget 70. The distinct signatures are
    # what keeps the mask cache from conflating the two states.
    m64 = JsonMachine(budget=64, budget_bucket=64)
    m70 = JsonMachine(budget=70, budget_bucket=64)
    assert m64.signature() != m70.signature()
    tok = b"1" * 63 + b","
    admit = []
    for mm in (m64, m70):
        for byte in b"[":
            assert mm.advance(byte)
        admit.append(all(mm.advance(byte) for byte in tok))
    assert admit == [False, True], admit
    # _AnyFrame plumbs the provider's max_token_bytes through.
    from runbookai_tpu.model.schema_guided import _AnyFrame

    fr = _AnyFrame(budget=100, budget_bucket=48)
    assert fr.m.budget_bucket == 49
    assert fr.copy().m.budget_bucket == 49
