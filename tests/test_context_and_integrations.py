"""Context managers, operability providers, claude-hooks integrations."""

import io
import json

import pytest

from runbookai_tpu.agent.infra_context import create_infra_context
from runbookai_tpu.agent.knowledge_context import KnowledgeContextManager
from runbookai_tpu.agent.orchestrator import ToolExecutor
from runbookai_tpu.agent.service_context import ServiceContextManager
from runbookai_tpu.agent.types import KnowledgeResult, RetrievedKnowledge
from runbookai_tpu.integrations.claude_hooks import (
    HookHandlers,
    hooks_status,
    install_hooks,
    run_hook_stdin,
    uninstall_hooks,
)
from runbookai_tpu.integrations.operability_ingestion import (
    IngestionClient,
    build_claims_from_hook_event,
)
from runbookai_tpu.integrations.session_store import (
    LocalSessionStore,
    ingest_sessions,
)
from runbookai_tpu.knowledge.store.graph import ServiceGraph
from runbookai_tpu.providers.operability import (
    ContextClaim,
    HTTPAdapter,
    LocalGraphAdapter,
    Provenance,
    create_adapter,
    reconcile_claims,
)
from runbookai_tpu.utils.config import Config


class StubRetriever:
    def __init__(self):
        self.queries = []

    async def retrieve(self, query, services=None):
        self.queries.append(query)
        if "payment" in query:
            return RetrievedKnowledge(runbooks=[KnowledgeResult(
                doc_id="rb-1", title="Payment runbook", knowledge_type="runbook",
                content="steps")])
        return RetrievedKnowledge()


async def test_knowledge_context_manager_primes_and_requeries():
    mgr = KnowledgeContextManager(StubRetriever())
    await mgr.prime("payment latency")
    block = mgr.system_prompt_block()
    assert "[rb-1] Payment runbook (runbook)" in block
    # already-seen terms don't requery
    assert await mgr.observe_terms(["payment"]) is None
    # new terms that match knowledge do
    result = await mgr.observe_terms(["payment-gateway"])
    assert result is not None and not result.empty


def test_service_context_manager_block():
    g = ServiceGraph()
    g.add_dependency("checkout-web", "payment-api")
    g.add_dependency("payment-api", "payments-db")
    g.add_service("payment-api", team="payments", tier=1)
    mgr = ServiceContextManager(g)
    added = mgr.observe_services(["payment-api", "unknown-svc"])
    assert added == ["payment-api"]
    block = mgr.system_prompt_block()
    assert "depends on: payments-db" in block
    assert "blast radius if degraded: checkout-web" in block


async def test_infra_context_discovery():
    from runbookai_tpu.tools import simulated as sim
    from runbookai_tpu.tools.registry import ToolRegistry

    reg = ToolRegistry()
    cloud = sim.SimulatedCloud()
    sim.register_aws(reg, cloud)
    sim.register_kubernetes(reg, cloud)
    executor = ToolExecutor({t.name: t for t in reg.all()})
    mgr = await create_infra_context(executor)
    block = mgr.system_prompt_block()
    assert "Firing alarms" in block and "payment-api" in block
    assert await create_infra_context(executor, enabled=False) is None


def test_reconcile_claims_merging():
    claims = [
        ContextClaim("payment-api", "deployed", confidence=0.5,
                     provenance=Provenance(source="a")),
        ContextClaim("payment-api", "deployed", confidence=0.6,
                     provenance=Provenance(source="b")),
        ContextClaim("payment-api", "scaled", confidence=0.1),
    ]
    merged = reconcile_claims(claims)
    assert len(merged) == 1  # low-confidence scaled dropped
    assert merged[0].predicate == "deployed"
    assert merged[0].confidence == pytest.approx(0.75)  # multi-source boost


async def test_local_graph_adapter_and_factory():
    g = ServiceGraph()
    g.add_dependency("a-svc", "b-svc")
    adapter = LocalGraphAdapter(graph=g)
    assert await adapter.blast_radius("b-svc") == ["a-svc"]
    facts = await adapter.fact_lookup("a-svc")
    assert facts["depends_on"] == ["b-svc"]

    cfg = Config.model_validate({"providers": {"operability_context": {
        "enabled": True, "adapter": "http", "base_url": "http://x"}}})
    assert isinstance(create_adapter(cfg), HTTPAdapter)
    cfg2 = Config.model_validate({"providers": {"operability_context": {
        "enabled": True, "adapter": "custom"}}})  # no base_url -> local fallback
    assert isinstance(create_adapter(cfg2, graph=g), LocalGraphAdapter)
    cfg3 = Config()
    assert create_adapter(cfg3) is None


def test_install_uninstall_hooks(tmp_path):
    settings = tmp_path / "settings.json"
    settings.write_text(json.dumps({"model": "opus", "hooks": {
        "PreToolUse": [{"hooks": [{"type": "command", "command": "other-tool"}]}]}}))
    install_hooks(settings)
    status = hooks_status(settings)
    assert all(status.values())
    data = json.loads(settings.read_text())
    assert data["model"] == "opus"  # preserved
    # other tool's hook preserved alongside ours
    pre = data["hooks"]["PreToolUse"]
    commands = [h["command"] for e in pre for h in e["hooks"]]
    assert "other-tool" in commands and any("runbook hook" in c for c in commands)
    # idempotent
    install_hooks(settings)
    assert json.loads(settings.read_text())["hooks"]["PreToolUse"] == pre
    assert uninstall_hooks(settings)
    status2 = hooks_status(settings)
    assert not any(status2.values())
    assert "other-tool" in json.dumps(json.loads(settings.read_text()))


def test_pre_tool_use_blocks_dangerous(tmp_path):
    handlers = HookHandlers(session_store=LocalSessionStore(tmp_path))
    blocked = handlers.handle_pre_tool_use(
        {"session_id": "s1", "tool_input": {"command": "kubectl delete pod x -n prod"}})
    assert blocked["decision"] == "block"
    ok = handlers.handle_pre_tool_use(
        {"session_id": "s1", "tool_input": {"command": "kubectl get pods"}})
    assert ok.get("continue") is True
    # rm -rf variants
    assert handlers.handle_pre_tool_use(
        {"tool_input": {"command": "rm -rf /data"}})["decision"] == "block"
    # stdin protocol: block -> exit code 2
    stdin = io.StringIO(json.dumps({"tool_input": {"command": "terraform destroy"}}))
    stdout = io.StringIO()
    code = run_hook_stdin("PreToolUse", handlers, stdin=stdin, stdout=stdout)
    assert code == 2 and json.loads(stdout.getvalue())["decision"] == "block"


def test_user_prompt_submit_injects_knowledge(tmp_path):
    from runbookai_tpu.knowledge.chunker import document_from_markdown
    from runbookai_tpu.knowledge.retriever import HybridRetriever, KnowledgeRetriever
    from runbookai_tpu.knowledge.store.sqlite_fts import KnowledgeStore

    store = KnowledgeStore(":memory:")
    store.upsert_document(document_from_markdown(
        "r.md", "---\ntype: known-issue\nservices: [payment-api]\n---\n"
                "# Pool exhaustion\n\npayment-api pool saturates under latency."))
    retriever = KnowledgeRetriever(store, HybridRetriever(store))
    handlers = HookHandlers(retriever=retriever)
    out = handlers.handle_user_prompt_submit(
        {"prompt": "why is payment-api latency so high?"})
    extra = out["hookSpecificOutput"]["additionalContext"]
    assert "Pool exhaustion" in extra
    # no terms -> no injection
    out2 = handlers.handle_user_prompt_submit({"prompt": "hello"})
    assert "hookSpecificOutput" not in out2


def test_session_store_and_ingestion(tmp_path):
    store = LocalSessionStore(tmp_path)
    store.append("sess/1", {"event": "PreToolUse", "tool_name": "Bash",
                            "tool_input": {"command": "kubectl get pods payment-api"}})
    store.append("sess/1", {"event": "PreToolUse", "decision": "block",
                            "tool_input": {"command": "rm -rf /"}})
    assert store.list_sessions() == ["sess_1"]
    assert len(store.read("sess/1")) == 2
    summary = ingest_sessions(store)
    assert summary["sessions"] == 1 and summary["events"] == 2
    assert summary["tool_counts"]["Bash"] == 1
    assert summary["blocked_commands"] == ["rm -rf /"]


def test_build_claims_and_spool(tmp_path):
    claims = build_claims_from_hook_event({
        "tool_name": "Bash",
        "tool_input": {"command": "kubectl rollout restart deployment/payment-api"},
    })
    assert claims and claims[0].predicate == "deployed"
    assert claims[0].subject == "payment-api"


async def test_ingestion_client_spool_and_replay(tmp_path):
    class FlakyAdapter:
        name = "flaky"
        capabilities = ("session_ingest",)
        fail = True

        def supports(self, c):
            return c in self.capabilities

        async def ingest_session(self, events):
            if self.fail:
                raise ConnectionError("down")
            return {"ok": len(events)}

    adapter = FlakyAdapter()
    client = IngestionClient(adapter, spool_dir=tmp_path)
    out = await client.ingest([{"e": 1}])
    assert out["status"] == "spooled"
    assert client.status()["spooled_batches"] == 1
    adapter.fail = False
    replay = await client.replay()
    assert replay == {"replayed": 1, "failed": 0}
    assert client.status()["spooled_batches"] == 0


# ---------------------------------------------------------------------------
# claude session → learning pipeline (reference learning/claude-session-ingestion.ts)

def _session_events():
    return [
        {"event_name": "UserPromptSubmit", "observed_at": "2026-03-01T10:00:00Z",
         "session_id": "s1",
         "payload": {"prompt": "payments api is throwing 503s"}},
        {"event_name": "PreToolUse", "observed_at": "2026-03-01T10:00:05Z",
         "session_id": "s1",
         "payload": {"tool_name": "Bash", "services": ["payments-api"]}},
        {"event_name": "PostToolUse", "observed_at": "2026-03-01T10:00:09Z",
         "session_id": "s1",
         "payload": {"tool_name": "Bash", "status": "ok",
                     "root_cause": "connection pool exhausted"}},
        {"event_name": "Stop", "observed_at": "2026-03-01T10:01:00Z",
         "session_id": "s1", "payload": {}},
    ]


def test_synthesize_result_from_session():
    from runbookai_tpu.learning.claude_session import (
        convert_session_to_events,
        describe_event,
        synthesize_result,
    )

    events = _session_events()
    result = synthesize_result("s1", events)
    assert result.summary["incident_id"] == "claude-s1"
    assert result.summary["query"] == "payments api is throwing 503s"
    assert result.root_cause == "connection pool exhausted"
    assert result.affected_services == ["payments-api"]
    assert result.confidence == "low"  # < 8 events
    timeline = convert_session_to_events(events)
    assert timeline[0].data["type"] == "claude_userpromptsubmit"
    assert timeline[1].data["phase"] == "tool"
    assert timeline[-1].data["phase"] == "conclude"
    assert "tool=Bash" in describe_event(events[1])


async def test_run_learning_from_session(tmp_path):
    from runbookai_tpu.learning.claude_session import run_learning_from_session

    class FakeLLM:
        async def complete(self, prompt):
            if "postmortem" in prompt.lower():
                return "# Postmortem\nit broke"
            return ('{"suggestions": [{"type": "runbook", "title": "Pool '
                    'exhaustion", "reason": "recurs", "services": '
                    '["payments-api"], "outline": "check pool"}]}')

    out = await run_learning_from_session(
        FakeLLM(), "s1", session_events=_session_events(), out_dir=tmp_path)
    assert (out / "postmortem-draft.md").exists()
    import json as _json
    suggestions = _json.loads((out / "knowledge-suggestions.json").read_text())
    assert suggestions["suggestions"][0]["title"] == "Pool exhaustion"
