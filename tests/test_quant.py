"""Int8 weight-only quantization: numerics, serving, and TP sharding.

SURVEY.md §7 hard part 4: bf16 70B doesn't fit v5e-16; int8 weight-only is
the memory path. These tests pin the scheme's invariants on the tiny config.
"""

import jax
import jax.numpy as jnp
import numpy as np

from runbookai_tpu.engine.engine import EngineConfig, EngineCore
from runbookai_tpu.engine.request import EngineRequest, SamplingParams
from runbookai_tpu.models.llama import CONFIGS, forward_train, init_params
from runbookai_tpu.models.quant import (
    LAYER_QUANT_KEYS,
    dequantize_params,
    dequantize_tensor,
    is_quantized,
    quantize_array_np,
    quantize_params,
    quantize_tensor,
    shardings_with_quant,
)
from runbookai_tpu.parallel.mesh import build_mesh
from runbookai_tpu.parallel.sharding import param_shardings
from runbookai_tpu.utils.tokens import ByteTokenizer

CFG = CONFIGS["llama3-test"]


def test_roundtrip_error_bounded():
    w = jax.random.normal(jax.random.PRNGKey(0), (2, 32, 48), dtype=jnp.float32)
    qt = quantize_tensor(w)
    assert qt["q"].dtype == jnp.int8 and qt["s"].shape == (2, 1, 48)
    back = dequantize_tensor(qt)
    # Symmetric rounding error is at most half a quantization step per element.
    assert np.all(np.abs(np.asarray(back - w)) <= np.asarray(qt["s"]) / 2 + 1e-7)


def test_numpy_and_jax_quantizers_agree():
    w = np.random.default_rng(0).normal(size=(3, 16, 8)).astype(np.float32)
    q_np, s_np = quantize_array_np(w)
    qt = quantize_tensor(jnp.asarray(w))
    np.testing.assert_array_equal(q_np, np.asarray(qt["q"]))
    np.testing.assert_allclose(s_np, np.asarray(qt["s"]), rtol=1e-6)


def test_quantize_params_structure_and_bytes():
    params = init_params(jax.random.PRNGKey(0), CFG, dtype=jnp.float32)
    qp = quantize_params(params)
    for k in LAYER_QUANT_KEYS:
        assert is_quantized(qp["layers"][k])
        # int8 payload is 1/4 the float32 bytes.
        assert qp["layers"][k]["q"].nbytes == params["layers"][k].nbytes // 4
    for k in ("attn_norm", "mlp_norm"):
        assert not is_quantized(qp["layers"][k])
    assert not is_quantized(qp["embed"])


def test_scale_after_matmul_equals_dequant_first():
    """(x @ q) * s must equal x @ (q * s) — the qmm identity."""
    params = init_params(jax.random.PRNGKey(1), CFG, dtype=jnp.float32)
    qp = quantize_params(params)
    deq = dequantize_params(qp, dtype=jnp.float32)
    tokens = jax.random.randint(jax.random.PRNGKey(2), (2, 12), 1, CFG.vocab_size)
    out_q = forward_train(qp, CFG, tokens)
    out_d = forward_train(deq, CFG, tokens)
    np.testing.assert_allclose(np.asarray(out_q), np.asarray(out_d),
                               atol=5e-3, rtol=5e-3)


def test_quantized_close_to_full_precision():
    params = init_params(jax.random.PRNGKey(1), CFG, dtype=jnp.float32)
    qp = quantize_params(params)
    tokens = jax.random.randint(jax.random.PRNGKey(3), (1, 16), 1, CFG.vocab_size)
    full = np.asarray(forward_train(params, CFG, tokens)).ravel()
    quant = np.asarray(forward_train(qp, CFG, tokens)).ravel()
    cos = float(np.dot(full, quant) / (np.linalg.norm(full) * np.linalg.norm(quant)))
    assert cos > 0.99, f"quantized logits diverged: cos={cos:.4f}"


def test_engine_serves_quantized_params():
    tok = ByteTokenizer()
    params = quantize_params(init_params(jax.random.PRNGKey(0), CFG, dtype=jnp.float32))
    core = EngineCore(CFG, params, tok, EngineConfig(
        page_size=4, num_pages=64, max_batch_slots=2, prefill_chunk=8,
        max_seq_len=128, block_pages=4, kv_dtype=jnp.float32))
    req = EngineRequest(prompt_ids=tok.encode("quantized serving"),
                        sampling=SamplingParams(temperature=0.0, max_new_tokens=6))
    core.submit(req)
    core.run_until_idle()
    assert req.finish_reason is not None and len(req.all_out_ids) >= 1


def test_tp_sharded_quantized_forward_matches():
    """Quantized forward over a (data=2, model=2) mesh == single-device."""
    params = quantize_params(init_params(jax.random.PRNGKey(0), CFG, dtype=jnp.float32))
    tokens = jax.random.randint(jax.random.PRNGKey(4), (2, 8), 1, CFG.vocab_size)
    ref = forward_train(params, CFG, tokens)

    mesh = build_mesh(2, 2)
    sh = shardings_with_quant(param_shardings(CFG, mesh), params)
    assert isinstance(sh["layers"]["wq"], dict)
    sharded = jax.tree.map(jax.device_put, params, sh)
    out = forward_train(sharded, CFG, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=5e-3, rtol=5e-3)


def test_init_params_quantized_structure_and_magnitude():
    """Direct-int8 random init (bench path for 8B-on-one-chip) matches the
    quantized-leaf format and the scaled-normal init magnitude."""
    from runbookai_tpu.models.llama import init_params_quantized

    p = init_params_quantized(jax.random.PRNGKey(0), CFG, dtype=jnp.float32)
    for k in LAYER_QUANT_KEYS:
        assert is_quantized(p["layers"][k]), k
        assert p["layers"][k]["q"].dtype == jnp.int8
    # Dequantized std ~ 1/sqrt(fan_in) (same as init_params' scaled normal).
    w = dequantize_tensor(p["layers"]["w_down"])  # fan_in = ffn_dim
    got = float(jnp.std(w))
    want = 1.0 / np.sqrt(CFG.ffn_dim)
    assert 0.5 * want < got < 1.5 * want, (got, want)
    # And it serves through the engine unchanged.
    tok = ByteTokenizer()
    core = EngineCore(CFG, p, tok, EngineConfig(
        page_size=4, num_pages=64, max_batch_slots=2, prefill_chunk=8,
        max_seq_len=128, block_pages=4, kv_dtype=jnp.float32))
    req = EngineRequest(prompt_ids=tok.encode("int8 init"),
                        sampling=SamplingParams(temperature=0.0, max_new_tokens=4))
    core.submit(req)
    core.run_until_idle()
    assert req.finish_reason is not None


def test_param_count_matches_tree():
    """Analytic matmul_params/total_params equal the actual pytree sizes."""
    p = init_params(jax.random.PRNGKey(0), CFG, dtype=jnp.float32)
    total = sum(x.size for x in jax.tree.leaves(p))
    assert total == CFG.total_params, (total, CFG.total_params)
    mm = sum(p["layers"][k].size for k in LAYER_QUANT_KEYS) + p["lm_head"].size
    assert mm == CFG.matmul_params, (mm, CFG.matmul_params)
    # North-star shape sanity: Llama-3-8B is 8.03B params.
    assert abs(CONFIGS["llama3-8b-instruct"].total_params - 8.03e9) < 0.02e9


def test_70b_int8_tp8_memory_plan_fits_v5e():
    """The documented 70B serving plan (int8 weights, tp=8, dp=2 on a
    v5e-16) must arithmetically fit the 16GB/chip HBM budget with KV-pool
    headroom — this is the math the sharded loader implements."""
    from runbookai_tpu.models.llama import CONFIGS

    cfg = CONFIGS["llama3-70b-instruct"]
    tp = 8
    hbm = 16 * 1024**3
    layer_matmul = cfg.matmul_params - cfg.dim * cfg.vocab_size
    int8_shard = layer_matmul / tp                      # 1 byte/param, sharded
    # Per-output-channel f32 scales: 4 bytes per output column (~dim-sized
    # rows); bounded by params/dim * 4.
    scales = layer_matmul / cfg.dim * 4 / tp
    embed = cfg.vocab_size * cfg.dim * 2 / tp           # bf16, vocab-sharded
    head = cfg.vocab_size * cfg.dim * 2 / tp
    norms = (cfg.n_layers * 2 + 1) * cfg.dim * 4        # f32, replicated
    weights_per_chip = int8_shard + scales + embed + head + norms
    assert weights_per_chip < 10.5 * 1024**3            # ~10GB/chip

    # Leaves >= 4GB for the KV pool: 70B GQA (8 kv heads sharded over tp=8
    # -> 1 head/chip), 128 head dim, 80 layers, bf16.
    kv_per_token = 80 * 2 * (cfg.n_kv_heads // tp) * 128 * 2
    budget = hbm - weights_per_chip - 1.5 * 1024**3     # runtime headroom
    tokens = budget / kv_per_token
    assert tokens > 80_000  # >80k pooled tokens/chip, e.g. 10 x 8k contexts


# --------------------------------------------------------------------- #
# Pallas quantized matmul (ops/qmm_pallas.py)                           #
# --------------------------------------------------------------------- #


def test_qmm_pallas_kernel_matches_xla_expression():
    """The streamed-int8 kernel computes exactly (x @ q) * s."""
    from runbookai_tpu.ops.qmm_pallas import qmm_pallas, qmm_pallas_eligible

    key = jax.random.PRNGKey(0)
    for m, k, n in [(8, 512, 1024), (3, 256, 512), (32, 1024, 1536),
                    (13, 96, 128)]:
        assert qmm_pallas_eligible(m, k, n)
        w = jax.random.normal(key, (k, n), jnp.float32) / k**0.5
        wq = quantize_tensor(w)
        x = jax.random.normal(jax.random.PRNGKey(1), (m, k), jnp.float32)
        ref = (x @ wq["q"].astype(x.dtype)) * wq["s"].astype(x.dtype)
        got = qmm_pallas(x, wq["q"], wq["s"].reshape(1, n), interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)


def test_qmm_pallas_eligibility_boundaries():
    from runbookai_tpu.ops.qmm_pallas import MAX_PALLAS_M, qmm_pallas_eligible

    assert qmm_pallas_eligible(1, 32, 128)
    assert not qmm_pallas_eligible(1, 33, 128)  # K not tileable
    assert not qmm_pallas_eligible(1, 32, 64)  # N below one lane tile
    assert not qmm_pallas_eligible(MAX_PALLAS_M + 1, 4096, 14336)  # prefill M


def test_qmm_dispatch_uses_kernel_only_when_eligible():
    """qmm(impl='pallas') must route eligible decode shapes through the
    kernel and silently keep the XLA expression elsewhere — same math."""
    from runbookai_tpu.models.llama import qmm

    key = jax.random.PRNGKey(2)
    # Eligible: [B, T, K] @ [K, N] with N % 128 == 0.
    w = quantize_tensor(jax.random.normal(key, (256, 512), jnp.float32))
    x = jax.random.normal(key, (4, 2, 256), jnp.float32)
    a = qmm(x, w, impl="pallas")
    b = qmm(x, w, impl="xla")
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=2e-5, atol=2e-5)
    # Ineligible (N=64): must still be correct via fallback.
    w2 = quantize_tensor(jax.random.normal(key, (256, 64), jnp.float32))
    np.testing.assert_allclose(np.asarray(qmm(x, w2, impl="pallas")),
                               np.asarray(qmm(x, w2, impl="xla")),
                               rtol=2e-5, atol=2e-5)


def test_engine_decode_matches_across_qmm_impls():
    """Greedy engine decode with qmm_impl='pallas' reproduces the XLA
    path's tokens on a config whose projections are kernel-eligible."""
    from runbookai_tpu.models.llama import LlamaConfig

    cfg = LlamaConfig(name="qmm-test", vocab_size=262, dim=128, n_layers=2,
                      n_heads=4, n_kv_heads=2, ffn_dim=256, max_seq_len=512,
                      rope_theta=10_000.0)
    tok = ByteTokenizer()
    params = quantize_params(init_params(jax.random.PRNGKey(3), cfg,
                                         dtype=jnp.float32))
    prompt = tok.encode("paged attention decode parity")
    outs = {}
    for impl in ("xla", "pallas"):
        core = EngineCore(cfg, params, tok, EngineConfig(
            page_size=4, num_pages=64, max_batch_slots=2, prefill_chunk=16,
            max_seq_len=256, kv_dtype=jnp.float32, speculative=False,
            qmm_impl=impl))
        req = EngineRequest(prompt_ids=list(prompt),
                            sampling=SamplingParams(max_new_tokens=8,
                                                    stop_token_ids=()))
        core.submit(req)
        core.run_until_idle()
        outs[impl] = req.out_ids
    assert outs["pallas"] == outs["xla"], outs


def test_70b_int8_tp16_kv_split_memory_plan():
    """tp=16 on 70B (past the 8 kv heads) now plans as model=8 × seq=2
    (parallel/kv_split.py): weights shard 16-way, the KV pool's TOKEN
    axis picks up the extra factor, and per-chip KV bytes shrink by the
    FULL tp — the r3 replication warning is gone."""
    from runbookai_tpu.models.llama import CONFIGS
    from runbookai_tpu.parallel.kv_split import plan_kv_split

    cfg = CONFIGS["llama3-70b-instruct"]
    plan = plan_kv_split(cfg, 16)
    assert (plan.kv_shards, plan.pg_shards) == (8, 2) and plan.split

    hbm = 16 * 1024**3
    tp = plan.tp
    layer_matmul = cfg.matmul_params - cfg.dim * cfg.vocab_size
    # wq/wo/FFN shard 16-way; wk/wv only 8-way (model axis). wk/wv are
    # 2 * dim * n_kv * hd per layer — a small slice of layer params.
    wkv = cfg.n_layers * 2 * cfg.dim * cfg.n_kv_heads * cfg.head_dim
    int8_shard = (layer_matmul - wkv) / tp + wkv / plan.kv_shards
    scales = layer_matmul / cfg.dim * 4 / tp
    embed = cfg.vocab_size * cfg.dim * 2 / tp
    head = cfg.vocab_size * cfg.dim * 2 / tp
    norms = (cfg.n_layers * 2 + 1) * cfg.dim * 4
    weights_per_chip = int8_shard + scales + embed + head + norms
    assert weights_per_chip < 6 * 1024**3  # ~2x headroom vs the tp8 plan

    # KV pool: heads /8 AND tokens /2 -> per-token bytes on a chip halve
    # relative to the tp8 plan.
    kv_per_token = (cfg.n_layers * 2 * (cfg.n_kv_heads // plan.kv_shards)
                    * cfg.head_dim * 2) / plan.pg_shards
    budget = hbm - weights_per_chip - 1.5 * 1024**3
    tokens = budget / kv_per_token
    assert tokens > 200_000  # >200k pooled tokens/chip at tp16
