"""Slack Socket Mode against an in-process fake server (zero egress).

Covers the reference's socket transport (src/slack/gateway.ts:531 parity,
r3 VERDICT missing #2): RFC 6455 handshake, masked client frames, ping/
pong, envelope ack-before-dispatch, and reconnect-on-disconnect — all
through the vendored client in server/slack_socket.py.
"""

import base64
import hashlib
import json
import socket
import struct
import threading

import pytest

from runbookai_tpu.server.slack_socket import (
    OP_CLOSE,
    OP_PING,
    OP_TEXT,
    MiniWebSocket,
    SocketModeClient,
)

_MAGIC = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"


class FakeSlackWS:
    """Minimal RFC 6455 *server* speaking the Socket Mode envelope flow."""

    def __init__(self, scripts):
        # scripts: list of per-connection lists of envelopes to send.
        self.scripts = list(scripts)
        self.received: list[dict] = []  # client acks, in order
        self.srv = socket.create_server(("127.0.0.1", 0))
        self.port = self.srv.getsockname()[1]
        self.thread = threading.Thread(target=self._serve, daemon=True)
        self.thread.start()

    # ------------------------------------------------------------ server

    def _serve(self):
        for script in self.scripts:
            conn, _ = self.srv.accept()
            try:
                self._handshake(conn)
                for step in script:
                    if step == "ping":
                        self._send_frame(conn, OP_PING, b"hb")
                        op, payload = self._recv_frame(conn)  # pong
                        assert op == 0xA and payload == b"hb"
                        continue
                    if step == "close":
                        self._send_frame(conn, OP_CLOSE,
                                         struct.pack(">H", 1000))
                        continue
                    self._send_frame(conn, OP_TEXT,
                                     json.dumps(step).encode())
                    if step.get("envelope_id"):
                        op, payload = self._recv_frame(conn)
                        assert op == OP_TEXT
                        self.received.append(json.loads(payload))
            finally:
                conn.close()

    def _handshake(self, conn):
        data = b""
        while b"\r\n\r\n" not in data:
            data += conn.recv(4096)
        key = next(line.split(":", 1)[1].strip()
                   for line in data.decode().split("\r\n")
                   if line.lower().startswith("sec-websocket-key"))
        accept = base64.b64encode(
            hashlib.sha1((key + _MAGIC).encode()).digest()).decode()
        conn.sendall((f"HTTP/1.1 101 Switching Protocols\r\n"
                      "Upgrade: websocket\r\nConnection: Upgrade\r\n"
                      f"Sec-WebSocket-Accept: {accept}\r\n\r\n").encode())

    @staticmethod
    def _send_frame(conn, opcode, payload):
        head = bytes([0x80 | opcode])  # servers do not mask
        n = len(payload)
        if n < 126:
            head += bytes([n])
        else:
            head += bytes([126]) + struct.pack(">H", n)
        conn.sendall(head + payload)

    @staticmethod
    def _recv_frame(conn):
        buf = b""
        while len(buf) < 2:
            buf += conn.recv(4096)
        opcode = buf[0] & 0x0F
        n = buf[1] & 0x7F
        need = 2
        if n == 126:
            while len(buf) < 4:
                buf += conn.recv(4096)
            n = struct.unpack(">H", buf[2:4])[0]
            need = 4
        elif n == 127:
            while len(buf) < 10:
                buf += conn.recv(4096)
            n = struct.unpack(">Q", buf[2:10])[0]
            need = 10
        need += 4 + n  # mask + payload (clients always mask)
        while len(buf) < need:
            buf += conn.recv(4096)
        mask = buf[need - 4 - n : need - n]
        payload = bytes(b ^ mask[i % 4]
                        for i, b in enumerate(buf[need - n : need]))
        return opcode, payload


def _envelope(env_id, text="<@U0BOT> investigate INC-1"):
    return {"type": "events_api", "envelope_id": env_id,
            "payload": {"event": {"type": "app_mention", "text": text,
                                  "channel": "C1", "user": "U2",
                                  "event_ts": "111.222"}}}


def test_socket_mode_handshake_envelopes_acks_and_reconnect():
    fake = FakeSlackWS([
        [{"type": "hello"}, "ping", _envelope("env-1"),
         {"type": "disconnect", "reason": "refresh_requested"}],
        [{"type": "hello"}, _envelope("env-2"), "close"],
    ])
    events = []
    client = SocketModeClient(
        "xapp-test", handler=events.append,
        connections_open=lambda tok: f"ws://127.0.0.1:{fake.port}/link",
        max_reconnects=1,
    )
    client.run()  # returns after the second connection's clean close
    fake.thread.join(timeout=10)

    assert [e["envelope_id"] for e in fake.received] == ["env-1", "env-2"]
    assert list(client.acked) == ["env-1", "env-2"]
    assert len(events) == 2
    assert events[0]["type"] == "app_mention"
    assert "investigate INC-1" in events[0]["text"]


def test_handshake_rejects_bad_accept_key():
    srv = socket.create_server(("127.0.0.1", 0))
    port = srv.getsockname()[1]

    def bad_server():
        conn, _ = srv.accept()
        data = b""
        while b"\r\n\r\n" not in data:
            data += conn.recv(4096)
        conn.sendall((b"HTTP/1.1 101 Switching Protocols\r\n"
                      b"Upgrade: websocket\r\nConnection: Upgrade\r\n"
                      b"Sec-WebSocket-Accept: bogus\r\n\r\n"))
        conn.close()

    t = threading.Thread(target=bad_server, daemon=True)
    t.start()
    with pytest.raises(ConnectionError, match="Accept"):
        MiniWebSocket.connect(f"ws://127.0.0.1:{port}/")


def test_large_server_frame_through_envelope_loop():
    """Server frames with 2-byte extended length (>=126 bytes) decode."""
    fake = FakeSlackWS([[
        {"type": "hello"},
        {"type": "events_api", "envelope_id": "big-1",
         "payload": {"event": {"type": "app_mention",
                               "text": "y" * 300}}},
        "close",
    ]])
    events = []
    client = SocketModeClient(
        "xapp-test", handler=events.append,
        connections_open=lambda tok: f"ws://127.0.0.1:{fake.port}/",
        max_reconnects=0,
    )
    client.run()
    assert events and len(events[0]["text"]) == 300
    assert list(client.acked) == ["big-1"]


def test_large_client_frame_masking_roundtrip():
    """Client-masked frames with 2- and 8-byte extended lengths decode to
    the original payload on the server side (socketpair, no handshake)."""
    a, b = socket.socketpair()
    try:
        ws = MiniWebSocket(a)
        for size in (300, 70_000):
            ws.send_frame(OP_TEXT, b"z" * size)
            opcode, payload = FakeSlackWS._recv_frame(b)
            assert opcode == OP_TEXT and payload == b"z" * size
    finally:
        a.close()
        b.close()
