"""Golden-weights loader parity vs the HF reference implementation.

VERDICT r2 weak #7 / next-round #7: ``models/hf_loader.py`` had never been
exercised against a real artifact — a transposed projection or a wrong GQA
head permutation would have passed the whole suite. The checked-in fixtures
(``tests/fixtures/hf-tiny-{untied,tied}``) are genuine ``save_pretrained``
outputs of tiny ``transformers.LlamaForCausalLM`` models (dim 64, 2 layers,
4 heads / 2 kv heads — real GQA) plus logits computed by transformers
itself; both the serving (paged) and training (dense) forwards must
reproduce them.

Fixtures were generated once with torch/transformers (seed 0, float32);
see the module docstring block at the bottom for the regeneration recipe.
"""

from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from runbookai_tpu.models.hf_loader import config_from_hf, load_params
from runbookai_tpu.models.llama import forward_train

FIXTURES = Path(__file__).parent / "fixtures"


def _load(name):
    d = FIXTURES / name
    cfg, params = load_params(d, config_from_hf(d, name=name),
                              dtype=jnp.float32)
    blob = np.load(d / "expected_logits.npz")
    return cfg, params, blob["input_ids"], blob["logits"]


@pytest.mark.parametrize("name,tied", [("hf-tiny-untied", False),
                                       ("hf-tiny-tied", True),
                                       ("hf-tiny-qwen2", False),
                                       ("hf-tiny-mixtral", False),
                                       ("hf-tiny-rope31", True)])
def test_train_forward_matches_hf_logits(name, tied):
    cfg, params, ids, want = _load(name)
    assert cfg.tie_embeddings is tied
    assert cfg.n_kv_heads == 2 and cfg.n_heads == 4  # real GQA layout
    if "qwen2" in name:
        # Qwen2 = same block + q/k/v biases; the loader must pick them up
        # (a dropped bias would still pass a llama-only suite).
        assert cfg.qkv_bias and "bq" in params["layers"]
    if "mixtral" in name:
        # 4-expert top-2 MoE; dropless capacity (config default), so
        # parity vs transformers is exact.
        assert cfg.n_experts == 4 and "router" in params["layers"]
    if "rope31" in name:
        # Llama-3.1 NTK-by-parts scaling; sequence runs past
        # original_max_pos so the interpolated band affects logits.
        assert cfg.rope_scaling == (8.0, 1.0, 4.0, 64)
    got = np.asarray(forward_train(params, cfg, jnp.asarray(ids)))
    # float32 end-to-end on both sides; tolerance covers op-order drift only.
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=2e-3)


@pytest.mark.parametrize("name", ["hf-tiny-untied", "hf-tiny-tied",
                                  "hf-tiny-qwen2", "hf-tiny-mixtral",
                                  "hf-tiny-rope31"])
def test_serving_forward_matches_hf_logits(name):
    """The paged serving forward (chunked prefill through the KV pool) must
    agree with the HF logits too — this is the path the engine actually
    runs, including the page-table scatter and GQA head grouping."""
    from runbookai_tpu.engine.kv_cache import KVCacheManager
    from runbookai_tpu.models.llama import forward_impl

    cfg, params, ids, want = _load(name)
    b, t = ids.shape
    page_size = 4
    kv = KVCacheManager(n_layers=cfg.n_layers, num_pages=64,
                        page_size=page_size, n_kv_heads=cfg.n_kv_heads,
                        head_dim=cfg.head_dim, max_seq_len=64,
                        dtype=jnp.float32)
    tables = np.zeros((b, kv.max_pages_per_seq + 1), dtype=np.int32)
    for i in range(b):
        rid = f"s{i}"
        kv.add_sequence(rid)
        kv.extend(rid, t)
        tables[i, : kv.max_pages_per_seq] = kv.page_table_row(rid)
    positions = np.broadcast_to(np.arange(t, dtype=np.int32), (b, t))
    ctx = np.full((b,), t, dtype=np.int32)
    logits, _, _ = forward_impl(
        params, cfg, jnp.asarray(ids), jnp.asarray(positions),
        kv.pool.kv_k, kv.pool.kv_v, jnp.asarray(tables), jnp.asarray(ctx),
        page_size=page_size,
    )
    np.testing.assert_allclose(np.asarray(logits), want, atol=2e-4, rtol=2e-3)


def test_loader_would_catch_a_transposed_projection():
    """Sanity that the tolerance actually bites: deliberately transpose one
    projection and assert parity FAILS — guards against a vacuous test."""
    cfg, params, ids, want = _load("hf-tiny-untied")
    broken = jax.tree.map(lambda x: x, params)  # shallow copy of the pytree
    wq = np.asarray(broken["layers"]["wq"])
    broken["layers"]["wq"] = jnp.asarray(np.swapaxes(wq, 1, 2))
    got = np.asarray(forward_train(broken, cfg, jnp.asarray(ids)))
    assert not np.allclose(got, want, atol=2e-4, rtol=2e-3)


# Regeneration recipe (run from the repo root; transformers+torch CPU):
#
#   cfg = transformers.LlamaConfig(vocab_size=256, hidden_size=64,
#       intermediate_size=128, num_hidden_layers=2, num_attention_heads=4,
#       num_key_value_heads=2, max_position_embeddings=512,
#       rms_norm_eps=1e-5, rope_theta=10000.0, tie_word_embeddings=<bool>,
#       attention_bias=False, mlp_bias=False)
#   torch.manual_seed(0); model = LlamaForCausalLM(cfg).eval().float()
#   model.save_pretrained("tests/fixtures/hf-tiny-<variant>")
#   ids = [[1,7,42,200,3,99,5,17],[2,250,11,0,88,123,45,6]]
#   np.savez_compressed(".../expected_logits.npz", input_ids=ids,
#                       logits=model(torch.tensor(ids)).logits.numpy())


def test_config_from_hf_family_and_sliding_window(tmp_path):
    import json as _json

    # Mistral v0.1-style config: the sliding window clamps the serveable
    # context (full attention is exact only up to the window).
    (tmp_path / "config.json").write_text(_json.dumps({
        "model_type": "mistral", "vocab_size": 32000, "hidden_size": 64,
        "num_hidden_layers": 2, "num_attention_heads": 4,
        "num_key_value_heads": 2, "intermediate_size": 128,
        "max_position_embeddings": 32768, "sliding_window": 4096,
    }))
    cfg = config_from_hf(tmp_path, name="downloaded-finetune")
    assert cfg.family == "mistral" and not cfg.qkv_bias
    assert cfg.max_seq_len == 4096

    # The chat format follows the checkpoint's model_type even when the
    # serving name says nothing about the family.
    from runbookai_tpu.model.chat_template import format_for_model
    assert format_for_model("downloaded-finetune", cfg.family) == "mistral"

    (tmp_path / "config.json").write_text(_json.dumps({
        "model_type": "gpt_bigcode", "vocab_size": 100, "hidden_size": 64,
        "num_hidden_layers": 2, "num_attention_heads": 4,
        "intermediate_size": 128,
    }))
    import pytest as _pytest
    with _pytest.raises(ValueError, match="not supported"):
        config_from_hf(tmp_path)

    # Unsupported rope_scaling schemes must refuse loudly — dropping them
    # would silently change long-context numerics.
    (tmp_path / "config.json").write_text(_json.dumps({
        "model_type": "llama", "vocab_size": 100, "hidden_size": 64,
        "num_hidden_layers": 2, "num_attention_heads": 4,
        "intermediate_size": 128,
        "rope_scaling": {"type": "linear", "factor": 4.0},
    }))
    with _pytest.raises(ValueError, match="rope_scaling"):
        config_from_hf(tmp_path)


def test_bge_encoder_matches_hf_bert():
    """The knowledge encoder (from-scratch JAX BERT) must reproduce
    transformers BertModel CLS embeddings from a real save_pretrained
    artifact — retrieval quality rides on these numerics."""
    from runbookai_tpu.models.bge import encode, load_params as bge_load

    d = FIXTURES / "hf-tiny-bert"
    cfg, params = bge_load(d, dtype=jnp.float32)
    blob = np.load(d / "expected_embeddings.npz")
    got = np.asarray(encode(params, cfg, jnp.asarray(blob["input_ids"]),
                            jnp.asarray(blob["attention_mask"])))
    np.testing.assert_allclose(got, blob["cls_norm"], atol=2e-4, rtol=2e-3)
