"""Incident simulator: seeded novel scenarios through the fixture seam.

Reference parity target: scripts/simulate/setup-incidents.sh provisions
real broken infra so investigations face something unseen; here the
generator perturbs the simulated providers into novel failure states with
machine-checkable ground truth (runbookai_tpu/simulate/generator.py).
"""

import json

import pytest

from runbookai_tpu.agent.agent import Agent
from runbookai_tpu.agent.types import LLMResponse, ToolCall
from runbookai_tpu.model.client import MockLLMClient
from runbookai_tpu.simulate import (
    FAULT_TYPES,
    Scenario,
    generate_scenario,
    generate_scenarios,
    to_eval_case,
)
from runbookai_tpu.tools import simulated as sim_tools
from runbookai_tpu.tools.registry import ToolRegistry


def test_generation_is_deterministic():
    a, b = generate_scenario(123), generate_scenario(123)
    assert a.to_json() == b.to_json()
    c = generate_scenario(124)
    assert c.truth != a.truth or c.fixtures != a.fixtures


@pytest.mark.parametrize("fault", sorted(FAULT_TYPES))
def test_every_fault_type_generates_valid_fixtures(fault):
    s = generate_scenario(5, fault_type=fault)
    assert s.truth["fault_type"] == fault
    f = s.fixtures
    # The structure the simulated providers consume.
    assert {"aws", "cloudwatch_alarms", "cloudwatch_logs", "kubernetes",
            "datadog", "prometheus", "pagerduty"} <= set(f)
    root = s.truth["root_cause_service"]
    assert any(a["service"] == root and a["state"] == "ALARM"
               for a in f["cloudwatch_alarms"])
    assert f"/ecs/{root}" in f["cloudwatch_logs"]
    assert f["pagerduty"][0]["id"] == s.scenario_id
    # Upstream services show propagated symptoms (the agent must walk the
    # chain, not stop at the first alarm).
    chain = s.truth["chain"]
    if chain.index(root) > 0:
        up = chain[0]
        assert any(a["service"] == up for a in f["cloudwatch_alarms"])
    # Round-trips through the scenario file format.
    assert Scenario.from_json(s.to_json()).truth == s.truth


def test_scenarios_are_novel_vs_checked_in_fixtures():
    """The generated incident must not exist in the canned fixture set —
    otherwise e2e investigations keep re-solving the same incident."""
    canned = json.dumps(sim_tools.default_fixtures())
    for s in generate_scenarios(6, seed=100):
        assert s.scenario_id not in canned
        root = s.truth["root_cause_service"]
        # The canned scenario is a payment-api incident; generated root
        # causes come from a disjoint service pool.
        assert f'"{root}"' not in canned, root


async def test_agent_investigates_injected_fault_end_to_end(tmp_path):
    """E2E: the agent's tools surface an injected fault that exists in no
    checked-in fixture (the VERDICT 'done' criterion)."""
    s = generate_scenario(77, fault_type="disk_full")
    root = s.truth["root_cause_service"]

    reg = ToolRegistry()
    sim = sim_tools.SimulatedCloud(s.fixtures)
    sim_tools.register_aws(reg, sim)
    sim_tools.register_kubernetes(reg, sim)
    sim_tools.register_incident(reg, sim, None)
    tools = reg.all()

    def tc(name, args):
        return ToolCall(id=f"c-{name}", name=name, args=args)

    llm = MockLLMClient([
        LLMResponse(content="", tool_calls=[
            tc("cloudwatch_alarms", {"state": "ALARM"}),
            tc("cloudwatch_logs", {"log_group": f"/ecs/{root}"}),
        ]),
        LLMResponse(content=f"Root cause: {root} disk full — writes "
                            "failing with ENOSPC. confidence high"),
    ])
    agent = Agent(llm, tools, scratchpad_root=str(tmp_path), persist=False)
    events = [e async for e in agent.run(s.query, incident_id=s.scenario_id)]
    kinds = [e.kind for e in events]
    assert kinds.count("tool_result") == 2
    # The injected (never-checked-in) fault reached the model's context.
    assert root in llm.calls[1]["user"]
    # Tool results are summarized into the prompt; the scratchpad keeps
    # the full injected payload (ENOSPC log line) for drill-down.
    results = [e.data for e in events if e.kind == "tool_result"]
    assert any("disk" in json.dumps(r).lower() or "space" in
               json.dumps(r).lower() for r in results)
    answer = next(e for e in events if e.kind == "answer")
    assert "disk full" in answer.data["text"]


def test_to_eval_case_scores_against_truth():
    from runbookai_tpu.evalsuite.scoring import score_investigation_result

    s = generate_scenario(9, fault_type="cert_expiry")
    case = to_eval_case(s)
    assert case.fixtures is s.fixtures
    good = {"root_cause": s.truth["root_cause"],
            "confidence": "high",
            "affected_services": [s.truth["root_cause_service"]],
            "summary": s.truth["root_cause"]}
    bad = {"root_cause": "cosmic rays", "confidence": "low",
           "affected_services": ["unrelated-svc"], "summary": "?"}
    assert score_investigation_result(case, good).total \
        > score_investigation_result(case, bad).total
    assert score_investigation_result(case, good).passed


async def test_simulated_github_serves_deploy_culprit_pr():
    """Deploy-caused faults plant a culprit PR in fixtures['github']; the
    simulated github_query tool must actually serve it (it was dead data
    before — no tool could reach the block)."""
    s = generate_scenario(11, fault_type="bad_deploy_5xx")
    root = s.truth["root_cause_service"]
    assert s.fixtures["github"], "deploy fault must plant a culprit PR"

    reg = ToolRegistry()
    sim = sim_tools.SimulatedCloud(s.fixtures)
    sim_tools.register_code(reg, sim)
    tool = {t.name: t for t in reg.all()}["github_query"]
    out = await tool.execute({"action": "recent_prs", "service": root})
    assert out["results"], out
    assert out["results"][0]["repo"] == root
    # fix_candidates filters by keyword against title+diff_hint.
    out2 = await tool.execute({"action": "fix_candidates",
                               "keywords": ["feature-flag"]})
    assert out2["results"]


# ------------------------------------------------------ real-infra seam


def test_provision_plan_covers_every_fault_family():
    """VERDICT r4 #8: every generated fault family must map onto a real
    break/teardown recipe (a new family without one raises at plan time,
    not silently)."""
    from runbookai_tpu.simulate.generator import FAULT_TYPES
    from runbookai_tpu.simulate.provision import provision_plan

    for i, fault in enumerate(sorted(FAULT_TYPES)):
        s = generate_scenario(100 + i, fault_type=fault)
        plan = provision_plan(s)
        assert plan.break_steps, fault
        assert plan.teardown_steps, fault
        rendered = plan.render()
        assert s.scenario_id in rendered
        # teardown printed before break: interrupted applies stay
        # reversible by hand.
        assert rendered.index("teardown") < rendered.index("break:")


def test_provision_refuses_gracefully_without_credentials(monkeypatch):
    from runbookai_tpu.simulate import provision as prov

    monkeypatch.setattr(prov, "aws_credentials_available", lambda: None)
    s = generate_scenario(7, fault_type="throttling_quota")
    plan, status = prov.provision(s, apply=True)
    assert plan.break_steps
    assert status.startswith("refused")


def test_provision_dry_run_never_touches_boto3(monkeypatch):
    import sys

    from runbookai_tpu.simulate import provision as prov

    monkeypatch.setitem(sys.modules, "boto3", None)  # import would fail
    s = generate_scenario(8, fault_type="network_partition")
    plan, status = prov.provision(s, apply=False)
    assert "dry-run" in status


def test_apply_refuses_on_unresolved_operator_inputs(monkeypatch):
    """Even WITH credentials, apply must refuse while any break step
    still needs site-specific input — never crash boto3 mid-recipe."""
    from runbookai_tpu.simulate import provision as prov

    monkeypatch.setattr(prov, "aws_credentials_available", lambda: "env")
    s = generate_scenario(9, fault_type="cert_expiry")
    plan = prov.provision_plan(s)
    status = prov.apply_plan(plan)
    assert status.startswith("refused: steps need operator input")
    assert "Certificate/PrivateKey" in status


# ------------------------------------------------- adversarial variants


def test_misleading_symptom_decoy_is_stale_and_wrong_family():
    s = generate_scenario(2, fault_type="db_pool_exhaustion",
                          adversarial="misleading_symptom")
    t = s.truth
    assert t["adversarial"] == "misleading_symptom"
    decoy, root = t["decoy_service"], t["root_cause_service"]
    assert decoy != root
    assert t["decoy_fault_type"] != t["fault_type"]
    # Decoy alarm exists and is STALE (changed before the real incident).
    decoy_alarms = [a for a in s.fixtures["cloudwatch_alarms"]
                    if a["service"] == decoy]
    real_alarms = [a for a in s.fixtures["cloudwatch_alarms"]
                   if a["service"] == root]
    assert decoy_alarms and real_alarms
    assert decoy_alarms[0]["stateChangedAt"] < real_alarms[0]["stateChangedAt"]
    # The recovery event closes the decoy's story.
    assert any("recovered" in e["title"]
               for e in s.fixtures["datadog"]["events"])


def test_keyword_overlap_alone_scores_zero_on_misleading_split():
    """THE discriminator (VERDICT r4 #4): a strategy that parrots the
    loudest alarm's service + log text passes every base scenario and
    FAILS the misleading split — simulate eval now measures reasoning,
    not keyword overlap."""
    from runbookai_tpu.evalsuite.scoring import score_investigation_result

    def parrot(s):
        def loud(a):
            v = a.get("currentValue", 0)
            return v if isinstance(v, (int, float)) else 0
        a = max(s.fixtures["cloudwatch_alarms"], key=loud)
        svc = a["service"]
        text = " ".join(l["message"] for l in
                        s.fixtures["cloudwatch_logs"].get(f"/ecs/{svc}", [])[:2])
        return {"root_cause": f"{svc}: {text}", "confidence": "high",
                "affected_services": [svc], "summary": text}

    for seed in (1, 2, 5):  # decoy alarm outshouts the real one
        base = generate_scenario(seed, fault_type="db_pool_exhaustion")
        adv = generate_scenario(seed, fault_type="db_pool_exhaustion",
                                adversarial="misleading_symptom")
        assert score_investigation_result(to_eval_case(base),
                                          parrot(base)).passed
        adv_score = score_investigation_result(to_eval_case(adv),
                                               parrot(adv))
        assert not adv_score.passed, (seed, adv_score)
        assert adv_score.dimensions["root_cause"] == 0.0


def test_two_fault_secondary_is_off_chain_and_scored_to_primary():
    s = generate_scenario(5, fault_type="cert_expiry",
                          adversarial="two_fault")
    t = s.truth
    sec = t["secondary"]
    assert sec["service"] not in t["chain"]
    assert sec["fault_type"] != t["fault_type"]
    # Secondary signals are live in fixtures.
    assert any(a["service"] == sec["service"]
               for a in s.fixtures["cloudwatch_alarms"])
    assert f"/ecs/{sec['service']}" in s.fixtures["cloudwatch_logs"]
    # Scoring stays anchored to the primary: naming only the secondary
    # must not pass.
    from runbookai_tpu.evalsuite.scoring import score_investigation_result

    case = to_eval_case(s)
    assert case.expected_services == [t["root_cause_service"]]
    wrong = {"root_cause": sec["root_cause"], "confidence": "high",
             "affected_services": [sec["service"]],
             "summary": sec["root_cause"]}
    assert not score_investigation_result(case, wrong).passed


def test_signal_dropout_removes_modality_with_meta_signal():
    seen = set()
    for seed in range(12):
        s = generate_scenario(seed, fault_type="memory_leak_oom",
                              adversarial="signal_dropout")
        dropped = s.truth["dropped"]
        seen.add(dropped)
        root = s.truth["root_cause_service"]
        if dropped == "logs":
            assert f"/ecs/{root}" not in s.fixtures["cloudwatch_logs"]
            assert any(e["reason"] == "DaemonSetDegraded"
                       for e in s.fixtures["kubernetes"]["events"])
        elif dropped == "alarms":
            assert s.fixtures["cloudwatch_alarms"] == []
            assert s.fixtures["prometheus"]["alerts"]  # survives
        else:
            assert s.fixtures["datadog"]["metrics"] == {}
    assert seen == {"logs", "alarms", "metrics"}  # all modalities exercised


def test_adversarial_generation_is_deterministic():
    for mode in ("misleading_symptom", "two_fault", "signal_dropout", "mix"):
        a = generate_scenario(9, adversarial=mode)
        b = generate_scenario(9, adversarial=mode)
        assert a.to_json() == b.to_json()


def test_mix_rotates_modes_by_seed():
    from runbookai_tpu.simulate.generator import ADVERSARIAL_MODES

    modes = {generate_scenario(s, adversarial="mix").truth["adversarial"]
             for s in range(6)}
    assert modes == set(ADVERSARIAL_MODES)
