"""Docs-site generator (reference parity: rendered docs/index.html)."""

import subprocess
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent


def test_build_docs_site_renders_all_docs(tmp_path):
    out = tmp_path / "site"
    subprocess.run(
        [sys.executable, str(ROOT / "scripts" / "build_docs_site.py"),
         "--out", str(out)], check=True, capture_output=True)
    h = (out / "index.html").read_text()
    assert 'id="doc-readme"' in h
    for doc in (ROOT / "docs").glob("*.md"):
        assert f'id="doc-{doc.stem.lower()}"' in h, doc
    # Code fences render escaped (no raw markdown backticks leak).
    assert "<pre><code>" in h and "```" not in h


def test_md_to_html_subset():
    sys.path.insert(0, str(ROOT / "scripts"))
    from build_docs_site import md_to_html

    h = md_to_html("# T\n\npara **b** `c`\n\n- a\n- b\n\n"
                   "| h |\n|---|\n| v |\n\n```\nx < y\n```")
    assert '<h1 id="t">T</h1>' in h
    assert "<strong>b</strong>" in h and "<code>c</code>" in h
    assert h.count("<li>") == 2
    assert "<th>h</th>" in h and "<td>v</td>" in h
    assert "x &lt; y" in h  # escaping inside fences
