"""MoE FFN op + expert parallelism on the CPU mesh.

Golden numerics vs transformers live in test_hf_golden.py (hf-tiny-mixtral);
here: the dispatch machinery itself (dropless equivalence against a direct
per-token reference, capacity dropping, int8 expert weights) and the EP
sharding path (expert axis over ``model``) matching the unsharded forward.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from runbookai_tpu.models.llama import CONFIGS, forward_train, init_params
from runbookai_tpu.ops.moe import expert_capacity, moe_ffn
from runbookai_tpu.parallel.mesh import build_mesh
from runbookai_tpu.parallel.sharding import param_shardings


def _ref_moe(y, router, wg, wu, wd, top_k):
    """Direct per-token reference: every token runs its top-k experts."""
    b, t, d = y.shape
    x = np.asarray(y, np.float64).reshape(-1, d)
    logits = x @ np.asarray(router, np.float64)
    ex = np.exp(logits - logits.max(axis=-1, keepdims=True))
    probs = ex / ex.sum(axis=-1, keepdims=True)
    out = np.zeros_like(x)
    for n in range(x.shape[0]):
        idx = np.argsort(-probs[n])[:top_k]
        w = probs[n, idx] / probs[n, idx].sum()
        for k, e in enumerate(idx):
            a = x[n] @ np.asarray(wg[e], np.float64)
            u = x[n] @ np.asarray(wu[e], np.float64)
            act = (a / (1 + np.exp(-a))) * u
            out[n] += w[k] * (act @ np.asarray(wd[e], np.float64))
    return out.reshape(b, t, d)


def _rand_moe(rng, e=4, d=16, f=32):
    router = rng.normal(size=(d, e)) * 0.5
    wg = rng.normal(size=(e, d, f)) / np.sqrt(d)
    wu = rng.normal(size=(e, d, f)) / np.sqrt(d)
    wd = rng.normal(size=(e, f, d)) / np.sqrt(f)
    return (jnp.asarray(x, jnp.float32) for x in (router, wg, wu, wd))


def test_moe_matches_per_token_reference_dropless():
    rng = np.random.default_rng(0)
    router, wg, wu, wd = _rand_moe(rng)
    y = jnp.asarray(rng.normal(size=(2, 5, 16)), jnp.float32)
    got = moe_ffn(y, router, wg, wu, wd, top_k=2, capacity_factor=4.0)
    want = _ref_moe(y, router, wg, wu, wd, top_k=2)
    np.testing.assert_allclose(np.asarray(got), want, atol=1e-5, rtol=1e-4)


def test_moe_capacity_drops_tokens():
    # With capacity 1 per expert, most (token, k) pairs drop and contribute
    # zero — the op must stay finite and under-count rather than corrupt.
    rng = np.random.default_rng(1)
    router, wg, wu, wd = _rand_moe(rng)
    y = jnp.asarray(rng.normal(size=(1, 8, 16)), jnp.float32)
    assert expert_capacity(8, 4, 2, 0.25) == 1
    tight = moe_ffn(y, router, wg, wu, wd, top_k=2, capacity_factor=0.25)
    loose = moe_ffn(y, router, wg, wu, wd, top_k=2, capacity_factor=4.0)
    assert np.all(np.isfinite(np.asarray(tight)))
    # Dropping must actually change the result (guards a vacuous clamp).
    assert not np.allclose(np.asarray(tight), np.asarray(loose))


def test_moe_int8_expert_weights():
    from runbookai_tpu.models.quant import quantize_tensor

    rng = np.random.default_rng(2)
    router, wg, wu, wd = _rand_moe(rng)
    y = jnp.asarray(rng.normal(size=(2, 4, 16)), jnp.float32)
    ref = moe_ffn(y, router, wg, wu, wd, top_k=2, capacity_factor=4.0)
    q = moe_ffn(y, router, quantize_tensor(wg), quantize_tensor(wu),
                quantize_tensor(wd), top_k=2, capacity_factor=4.0)
    # int8 weight-only: close but not exact.
    np.testing.assert_allclose(np.asarray(q), np.asarray(ref),
                               atol=0.05, rtol=0.1)


def test_expert_capacity_bounds():
    assert expert_capacity(16, 4, 2, 2.0) == 16   # clamped at N
    assert expert_capacity(16, 8, 2, 1.0) == 4
    assert expert_capacity(3, 8, 2, 0.1) == 1     # floor at 1


CFG = CONFIGS["mixtral-test"]  # E=4, top-2


def test_ep_sharded_forward_matches_unsharded():
    """Expert-parallel placement (E over ``model``) must not change the
    forward — XLA inserts the dispatch/combine collectives."""
    params = init_params(jax.random.PRNGKey(0), CFG, dtype=jnp.float32)
    tokens = jnp.asarray(
        np.random.default_rng(3).integers(0, CFG.vocab_size, (2, 12)),
        jnp.int32)
    ref = forward_train(params, CFG, tokens)

    mesh = build_mesh(2, 4)  # tp=4 divides E=4 -> EP active
    sh = param_shardings(CFG, mesh)
    assert "model" in str(sh["layers"]["w_gate"].spec)
    assert sh["layers"]["router"].spec == jax.sharding.PartitionSpec()
    placed = jax.tree.map(jax.device_put, params, sh)
    got = forward_train(placed, CFG, tokens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_moe_config_param_counts():
    # Active (FLOPs) vs total (memory) split: 4 experts, top-2.
    dense_ffn = 3 * CFG.dim * CFG.ffn_dim
    assert CFG.matmul_params < CFG.total_params
    active_ffn = CFG.top_k_experts * dense_ffn
    all_ffn = CFG.n_experts * dense_ffn
    assert (CFG.total_params - CFG.matmul_params
            ) >= (all_ffn - active_ffn) * CFG.n_layers - CFG.dim


async def test_mixtral_engine_generates():
    from runbookai_tpu.model.jax_tpu import JaxTpuClient

    client = JaxTpuClient.for_testing("mixtral-test")
    assert client.chat_format == "mistral"
    resp = await client.chat("You are terse.", "hello")
    assert isinstance(resp.content, str)
    assert resp.usage["completion_tokens"] > 0
    await client.shutdown()


def test_moe_train_grads_flow():
    # Gradients must reach router AND experts (a detached router would
    # silently freeze routing during fine-tuning).
    params = init_params(jax.random.PRNGKey(1), CFG, dtype=jnp.float32)
    tokens = jnp.asarray(
        np.random.default_rng(4).integers(1, CFG.vocab_size, (2, 8)),
        jnp.int32)

    def loss(p):
        logits = forward_train(p, CFG, tokens[:, :-1])
        lab = jax.nn.one_hot(tokens[:, 1:], CFG.vocab_size)
        return -jnp.mean(jnp.sum(jax.nn.log_softmax(logits) * lab, -1))

    g = jax.grad(loss)(params)
    assert float(jnp.abs(g["layers"]["router"]).max()) > 0
    assert float(jnp.abs(g["layers"]["w_gate"]).max()) > 0

