"""TP-sharded serving engine on a CPU mesh (VERDICT r1 weak #2 / next #3).

The paged serving forward is a different code path from ``forward_train`` —
the 70B-TP serving claim needs EngineCore itself proven on a >1-device mesh:
sharded params + sharded KV pool through the full continuous-batching cycle
(chunked prefill, batched decode, preemption-by-recompute, prefix cache),
with greedy outputs matching the unsharded engine.
"""

import jax
import jax.numpy as jnp
import pytest

from runbookai_tpu.engine.engine import EngineConfig, EngineCore
from runbookai_tpu.engine.request import EngineRequest, SamplingParams
from runbookai_tpu.models.llama import CONFIGS, init_params
from runbookai_tpu.parallel.mesh import MODEL_AXIS, build_mesh
from runbookai_tpu.parallel.sharding import param_shardings
from runbookai_tpu.utils.tokens import ByteTokenizer

CFG = CONFIGS["llama3-test"]


@pytest.fixture(scope="module")
def setup():
    tok = ByteTokenizer()
    params = init_params(jax.random.PRNGKey(0), CFG, dtype=jnp.float32)
    mesh = build_mesh(1, 2)  # data=1, model=2 of the 8 virtual CPU devices
    sharded = jax.tree.map(jax.device_put, params, param_shardings(CFG, mesh))
    return tok, params, mesh, sharded


def make_core(tok, params, mesh=None, **kw):
    defaults = dict(
        page_size=4, num_pages=64, max_batch_slots=4, prefill_chunk=8,
        max_seq_len=128, block_pages=4, kv_dtype=jnp.float32,
    )
    defaults.update(kw)
    return EngineCore(CFG, params, tok, EngineConfig(**defaults), mesh=mesh)


def greedy(core, prompts, max_new=8):
    reqs = [
        EngineRequest(prompt_ids=list(p),
                      sampling=SamplingParams(temperature=0.0, max_new_tokens=max_new))
        for p in prompts
    ]
    for r in reqs:
        core.submit(r)
    core.run_until_idle()
    return reqs


def test_kv_pool_is_sharded_on_model_axis(setup):
    tok, params, mesh, sharded = setup
    core = make_core(tok, sharded, mesh=mesh)
    spec = core._kv_k.sharding.spec
    assert spec[2] == MODEL_AXIS, spec
    # Per-device shard holds half the kv heads.
    shard_shape = core._kv_k.addressable_shards[0].data.shape
    assert shard_shape[2] == CFG.n_kv_heads // 2


@pytest.mark.parametrize("attn_impl", ["xla", "pallas"])
def test_sharded_engine_matches_unsharded_greedy(setup, attn_impl):
    """The TP engine must agree with the unsharded engine on BOTH attention
    backends — ``pallas`` runs per head-shard via shard_map (interpret mode
    on the CPU mesh; Mosaic on hardware). VERDICT r2 next-round #3."""
    tok, params, mesh, sharded = setup
    prompts = [
        tok.encode("investigate high latency in checkout"),
        tok.encode("pods crashlooping in payments namespace"),
        tok.encode("error rate spike after deploy"),
    ]
    ref = greedy(make_core(tok, params), prompts)
    got = greedy(make_core(tok, sharded, mesh=mesh, attn_impl=attn_impl),
                 prompts)
    for r, g in zip(ref, got):
        assert g.out_ids == r.out_ids
        assert g.finish_reason == r.finish_reason


def test_sharded_engine_preemption_cycle(setup):
    """Tiny page pool forces preemption on the sharded engine; every request
    still completes and the KV pool stays sharded across the cycle."""
    tok, params, mesh, sharded = setup
    prompts = [tok.encode("a" * 21), tok.encode("b" * 21)]
    # 19 usable pages: each sequence at full length needs 16, so two can only
    # run together until the pool forces an eviction (same scenario as
    # test_engine.test_forced_preemption_mid_decode, now on the mesh).
    solos = [greedy(make_core(tok, params), [p], max_new=40)[0] for p in prompts]
    core = make_core(tok, sharded, mesh=mesh, num_pages=20, max_batch_slots=2)
    core.ecfg.decode_steps_per_dispatch = 1
    core.ecfg.admit_headroom_tokens = 8
    reqs = greedy(core, prompts, max_new=40)
    assert core.metrics["preemptions"] >= 1, "scenario must actually preempt"
    for r, solo in zip(reqs, solos):
        assert r.all_out_ids == solo.all_out_ids
    assert core.kv.allocator.free_pages == 20 - 1
    assert core._kv_k.sharding.spec[2] == MODEL_AXIS


def test_sharded_prefix_cache_reuse(setup):
    """Second request with a shared page-aligned prefix skips cached pages."""
    tok, params, mesh, sharded = setup
    core = make_core(tok, sharded, mesh=mesh)
    shared = tok.encode("system prompt: you are an SRE agent. " * 2)
    a = greedy(core, [shared + tok.encode("q1")], max_new=4)[0]
    b = greedy(core, [shared + tok.encode("q2")], max_new=4)[0]
    assert a.finish_reason is not None and b.finish_reason is not None
    assert core.metrics["cached_prefix_tokens"] > 0


# --------------------------------------------------------------------- #
# KV page-split serving (tp > n_kv_heads — parallel/kv_split.py)        #
# --------------------------------------------------------------------- #


@pytest.fixture(scope="module")
def kvsplit_setup():
    """llama3-test has n_kv=2, n_heads=4 → tp=4 plans as model=2 × seq=2
    (group 2, pg_shards 2). Per-chip KV bytes shrink by the FULL tp."""
    from runbookai_tpu.parallel.kv_split import plan_kv_split

    tok = ByteTokenizer()
    params = init_params(jax.random.PRNGKey(0), CFG, dtype=jnp.float32)
    plan = plan_kv_split(CFG, 4)
    assert (plan.kv_shards, plan.pg_shards) == (2, 2) and plan.split
    mesh = build_mesh(1, model=plan.kv_shards, seq=plan.pg_shards)
    sharded = jax.tree.map(jax.device_put, params,
                           param_shardings(CFG, mesh))
    return tok, params, mesh, sharded


def test_kv_split_pool_shards_by_full_tp(kvsplit_setup):
    from runbookai_tpu.parallel.mesh import SEQ_AXIS

    tok, params, mesh, sharded = kvsplit_setup
    core = make_core(tok, sharded, mesh=mesh)
    spec = core._kv_k.sharding.spec
    assert spec[1] == SEQ_AXIS and spec[2] == MODEL_AXIS, spec
    ratio = (core._kv_k.nbytes
             // core._kv_k.addressable_shards[0].data.nbytes)
    assert ratio == 4, "per-chip KV bytes must shrink by the full tp"


def test_kv_split_engine_matches_unsharded_greedy(kvsplit_setup):
    """Full continuous-batching cycle on the page-split mesh reproduces
    the unsharded engine's greedy tokens (r3 VERDICT weak #6)."""
    tok, params, mesh, sharded = kvsplit_setup
    prompts = [
        tok.encode("investigate high latency in checkout"),
        tok.encode("pods crashlooping in payments namespace"),
        tok.encode("error rate spike after deploy"),
    ]
    ref = greedy(make_core(tok, params), prompts)
    got = greedy(make_core(tok, sharded, mesh=mesh), prompts)
    for r, g in zip(ref, got):
        assert g.out_ids == r.out_ids
        assert g.finish_reason == r.finish_reason


def test_kv_split_plan_boundaries():
    from runbookai_tpu.parallel.kv_split import plan_kv_split

    class Cfg70B:
        n_kv_heads = 8
        n_heads = 64

    p = plan_kv_split(Cfg70B, 16)
    assert (p.kv_shards, p.pg_shards) == (8, 2) and p.split
    p8 = plan_kv_split(Cfg70B, 8)
    assert (p8.kv_shards, p8.pg_shards) == (8, 1) and not p8.split
    # group=8 caps the page split at 8 → tp 128 ok, beyond raises
    assert plan_kv_split(Cfg70B, 64).pg_shards == 8
    with pytest.raises(ValueError):
        plan_kv_split(Cfg70B, 256)


def test_kv_split_write_never_wraps_into_foreign_slots():
    """Regression (r4 review): a foreign page's destination is NEGATIVE on
    higher seq shards; .at[].set(mode='drop') drops only OOB-HIGH indices
    while negative ones wrap Python-style — a write to page 0 must not
    corrupt shard 1's mirror slot."""
    import numpy as np

    from runbookai_tpu.ops.attention import write_kv_pages_batch
    from runbookai_tpu.parallel.kv_split import (
        write_kv_pages_batch_kv_split,
    )

    mesh = build_mesh(1, model=2, seq=2)
    ps, num_pages, n_kv, hd = 4, 8, 2, 8
    tokens = num_pages * ps
    pool = jnp.zeros((tokens, n_kv, hd), jnp.float32)
    new_kv = jnp.ones((1, 2, n_kv, hd), jnp.float32)
    pos = jnp.asarray([[0, 1]], jnp.int32)
    tables = jnp.asarray([[1, 0, 0, 0]], jnp.int32)  # page 1 -> shard 0
    want = write_kv_pages_batch(pool, new_kv, pos, tables, ps)
    got = write_kv_pages_batch_kv_split(mesh, pool, new_kv, pos, tables, ps)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    # The mirror slots on shard 1 (tokens 16+4..) must remain zero.
    assert float(jnp.abs(got[tokens // 2:]).max()) == 0.0


def test_kv_split_rejects_ragged_page_pool():
    from runbookai_tpu.parallel.kv_split import paged_attention_kv_split

    mesh = build_mesh(1, model=2, seq=2)
    ps, n_kv, hd = 4, 2, 8
    k = jnp.zeros((63 * ps, n_kv, hd), jnp.float32)  # 63 pages, pg=2
    with pytest.raises(ValueError, match="divide"):
        paged_attention_kv_split(
            mesh, jnp.zeros((1, 1, 4, hd), jnp.float32), k, k,
            jnp.zeros((1, 4), jnp.int32), jnp.ones((1,), jnp.int32),
            jnp.zeros((1, 1), jnp.int32), page_size=ps)


def test_kv_split_pallas_decode_matches_xla(kvsplit_setup):
    """The Pallas partial kernel + seq-merge must equal the XLA kv-split
    path AND the unsharded reference at decode shapes (interpret mode on
    the CPU mesh; Mosaic on hardware)."""
    import numpy as np

    from runbookai_tpu.ops.attention import paged_attention
    from runbookai_tpu.parallel.kv_split import (
        paged_attention_kv_split,
        paged_decode_attention_kv_split_pallas,
    )

    tok, params, mesh, sharded = kvsplit_setup
    rng = np.random.default_rng(5)
    n_q, n_kv, hd, ps = CFG.n_heads, CFG.n_kv_heads, CFG.head_dim, 4
    num_pages, max_pages = 16, 8
    tokens = num_pages * ps
    k_flat = jnp.asarray(rng.normal(size=(tokens, n_kv, hd)), jnp.float32)
    v_flat = jnp.asarray(rng.normal(size=(tokens, n_kv, hd)), jnp.float32)
    ctx_lens = [9, 17]
    tables = np.zeros((2, max_pages), np.int32)
    alloc = list(range(1, 16))
    rng.shuffle(alloc)
    for i, c in enumerate(ctx_lens):
        for p in range((c + ps - 1) // ps):
            tables[i, p] = alloc.pop()
    tables = jnp.asarray(tables)
    ctx = jnp.asarray(ctx_lens, jnp.int32)
    q = jnp.asarray(rng.normal(size=(2, n_q, hd)), jnp.float32)

    want = paged_attention(q[:, None], k_flat, v_flat, tables, ctx,
                           (ctx - 1)[:, None], page_size=ps)[:, 0]
    xla = paged_attention_kv_split(mesh, q[:, None], k_flat, v_flat,
                                   tables, ctx, (ctx - 1)[:, None],
                                   page_size=ps, block_pages=4)[:, 0]
    got = paged_decode_attention_kv_split_pallas(
        mesh, q, k_flat, v_flat, tables, ctx, page_size=ps, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(got), np.asarray(xla),
                               atol=1e-5, rtol=1e-5)


def test_kv_split_engine_pallas_matches_unsharded(kvsplit_setup):
    """Full engine cycle on the page-split mesh with attn_impl='pallas':
    decode runs the partial kernel, prefill the XLA kv-split path —
    greedy outputs must equal the unsharded engine."""
    tok, params, mesh, sharded = kvsplit_setup
    prompts = [tok.encode("kv split pallas decode parity check")]
    ref = greedy(make_core(tok, params), prompts)
    got = greedy(make_core(tok, sharded, mesh=mesh, attn_impl="pallas"),
                 prompts)
    assert got[0].out_ids == ref[0].out_ids


def test_qmm_probe_runs_under_multidevice_mesh():
    """ADVICE r4 medium: a DP-only multi-device mesh keeps qmm_impl=
    'pallas' in the model forward, so the init-time probe must compile
    the kernel under THAT mesh (replicated operands, GSPMD partitioning)
    — a partitioning failure has to downgrade at init, not crash the
    first dispatch."""
    from runbookai_tpu.engine.engine import (
        _probe_qmm_pallas_cached,
    )

    mesh = build_mesh(data=8)
    assert mesh.size == 8
    assert _probe_qmm_pallas_cached(
        "cpu", 8, 256, 512, "bfloat16", mesh=mesh)


def test_engine_int8_dp_mesh_serves(setup):
    """int8 weights + multi-device DP-only mesh + qmm auto path: engine
    construction runs the mesh-aware probe and the first dispatch must
    not crash (the ADVICE r4 failure mode)."""
    from runbookai_tpu.models.quant import quantize_params

    from runbookai_tpu.parallel.mesh import replicated

    tok, params, mesh, _ = setup
    dp_mesh = build_mesh(data=2)
    qparams = quantize_params(params)
    rep = jax.tree.map(
        lambda a: jax.device_put(a, replicated(dp_mesh)), qparams)
    prompts = [tok.encode("dp int8 qmm probe parity")]
    ref = greedy(make_core(tok, qparams), prompts)
    got = greedy(make_core(tok, rep, mesh=dp_mesh, qmm_impl="pallas"),
                 prompts)
    assert got[0].out_ids == ref[0].out_ids
