"""Serving metrics layer: registry semantics, Prometheus exposition, the
/metrics endpoint over a live server, request-ID-correlated tracing, and the
metric-name/bucket contract that pins dashboard-facing names at test time.
"""

import json
import re
import threading
import urllib.request
import warnings

import pytest

from runbookai_tpu.utils.metrics import (
    METRIC_NAME_RE,
    Histogram,
    MetricsRegistry,
    get_registry,
)

# --------------------------------------------------------------------------- #
# registry semantics                                                          #
# --------------------------------------------------------------------------- #


def test_counter_and_gauge_semantics():
    reg = MetricsRegistry()
    c = reg.counter("runbook_test_total", "test counter")
    c.inc()
    c.inc(2.5)
    assert c.value == 3.5
    with pytest.raises(ValueError):
        c.inc(-1)

    g = reg.gauge("runbook_test_gauge", "test gauge")
    g.set(5)
    g.inc()
    g.dec(2)
    assert g.value == 4

    fn_g = reg.gauge("runbook_test_fn_gauge", "callback gauge")
    fn_g.set_function(lambda: 42.0)
    assert fn_g.value == 42.0
    # A dying callback must not poison the scrape.
    fn_g.set_function(lambda: 1 / 0)
    assert "runbook_test_fn_gauge" in reg.render()


def test_labeled_scrape_time_callbacks():
    """Per-labelset set_function (the fleet's per-replica gauges): each
    labelset samples its own callback at scrape time, callbacks shadow any
    stored value for their key, a dying callback drops only its own
    series, and clear_functions() unbinds a rebuilt fleet's stale keys."""
    reg = MetricsRegistry()
    g = reg.gauge("runbook_test_replica_gauge", "per-replica",
                  labels=("replica",))
    state = {"0": 7.0, "1": 11.0}
    g.labels(replica="0").set_function(lambda: state["0"])
    g.labels(replica="1").set_function(lambda: state["1"])
    text = reg.render()
    assert 'runbook_test_replica_gauge{replica="0"} 7' in text
    assert 'runbook_test_replica_gauge{replica="1"} 11' in text
    state["0"] = 8.0
    assert 'replica="0"} 8' in reg.render()
    # The callback shadows a stored value for the same key...
    g.labels(replica="0").set(99)
    assert 'replica="0"} 8' in reg.render()
    # ...and a dying callback drops only its own series — including any
    # stale stored value for its key (never resurfaced as live data).
    g.labels(replica="1").set(55)
    g.labels(replica="1").set_function(lambda: 1 / 0)
    text = reg.render()
    assert 'replica="0"} 8' in text and 'replica="1"' not in text
    g.clear_functions()
    # Stored values resurface once callbacks are gone.
    assert 'replica="0"} 99' in reg.render()
    # Histograms have no per-key callbacks — observe() is the only input.
    h = reg.histogram("runbook_test_cb_hist", "h", buckets=(1.0,),
                      labels=("replica",))
    with pytest.raises(ValueError):
        h.labels(replica="0").set_function(lambda: 1.0)
    # Wrong arity is rejected like labels() itself rejects it.
    with pytest.raises(ValueError):
        g._set_key_function(("a", "b"), lambda: 0.0)


def test_labels_and_get_or_create():
    reg = MetricsRegistry()
    c = reg.counter("runbook_req_total", "reqs", labels=("route", "status"))
    c.labels(route="/a", status="200").inc()
    c.labels("/a", "200").inc()
    c.labels(route="/b", status="500").inc()
    text = reg.render()
    assert 'runbook_req_total{route="/a",status="200"} 2' in text
    assert 'runbook_req_total{route="/b",status="500"} 1' in text
    with pytest.raises(ValueError):
        c.inc()  # labeled metric requires .labels()
    with pytest.raises(ValueError):
        c.labels(route="/a").inc()  # wrong arity

    # get-or-create: same name returns the SAME object...
    assert reg.counter("runbook_req_total", "reqs",
                       labels=("route", "status")) is c
    # ...but type or label mismatches are loud, never silent aliasing.
    with pytest.raises(ValueError):
        reg.gauge("runbook_req_total", "reqs")
    with pytest.raises(ValueError):
        reg.counter("runbook_req_total", "reqs", labels=("route",))
    # Bucket mismatches too: re-registering a histogram with different
    # bounds must not silently keep the old layout.
    h = reg.histogram("runbook_gc_seconds", "x", buckets=(1.0, 2.0))
    assert reg.histogram("runbook_gc_seconds", "x", buckets=(1, 2)) is h
    with pytest.raises(ValueError):
        reg.histogram("runbook_gc_seconds", "x", buckets=(1.0, 5.0))


def test_name_and_bucket_validation():
    reg = MetricsRegistry()
    with pytest.raises(ValueError):
        reg.counter("bad_name_total", "no runbook_ prefix")
    with pytest.raises(ValueError):
        reg.counter("runbook_UPPER_total", "case")
    with pytest.raises(ValueError):
        reg.histogram("runbook_h_seconds", "x", buckets=())
    with pytest.raises(ValueError):
        reg.histogram("runbook_h_seconds", "x", buckets=(1.0, 0.5))
    with pytest.raises(ValueError):
        reg.histogram("runbook_h_seconds", "x", buckets=(1.0, float("inf")))
    with pytest.raises(ValueError):
        reg.counter("runbook_c_total", "x", labels=("le",))  # reserved


def test_histogram_bucket_boundaries():
    reg = MetricsRegistry()
    h = reg.histogram("runbook_lat_seconds", "latency",
                      buckets=(0.1, 1.0, 10.0))
    h.observe(0.1)   # ON the boundary: le="0.1" is cumulative <=
    h.observe(0.5)
    h.observe(100.0)  # +Inf bucket only
    text = reg.render()
    assert 'runbook_lat_seconds_bucket{le="0.1"} 1' in text
    assert 'runbook_lat_seconds_bucket{le="1"} 2' in text
    assert 'runbook_lat_seconds_bucket{le="10"} 2' in text
    assert 'runbook_lat_seconds_bucket{le="+Inf"} 3' in text
    assert "runbook_lat_seconds_count 3" in text
    assert "runbook_lat_seconds_sum 100.6" in text
    assert h.count == 3
    h.reset()
    assert h.count == 0


def test_histogram_percentile_interpolation():
    reg = MetricsRegistry()
    h = reg.histogram("runbook_p_seconds", "p", buckets=(1.0, 2.0, 4.0))
    assert h.percentile(95) is None  # empty
    for v in (0.5, 1.5, 3.0, 3.5):
        h.observe(v)
    assert h.percentile(50) == pytest.approx(2.0)
    assert 2.0 < h.percentile(95) <= 4.0
    h.observe(1000.0)  # +Inf: clamps to last finite bound
    assert h.percentile(99) == 4.0


def test_prometheus_escaping():
    reg = MetricsRegistry()
    c = reg.counter("runbook_esc_total", 'help "quoted" \\ and\nnewline',
                    labels=("tool",))
    c.labels(tool='a"b\\c\nd').inc()
    text = reg.render()
    assert "# HELP runbook_esc_total" in text
    assert "and\\nnewline" in text  # help newline escaped
    assert '{tool="a\\"b\\\\c\\nd"} 1' in text  # label value escaped


def test_concurrent_increments_from_threads():
    reg = MetricsRegistry()
    c = reg.counter("runbook_conc_total", "x")
    h = reg.histogram("runbook_conc_seconds", "x", buckets=(0.5, 1.0))

    def work():
        for _ in range(500):
            c.inc()
            h.observe(0.25)

    threads = [threading.Thread(target=work) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == 4000
    assert h.count == 4000
    assert h.sum == pytest.approx(1000.0)


def test_snapshot_is_json_friendly():
    reg = MetricsRegistry()
    reg.counter("runbook_s_total", "x").inc(3)
    reg.gauge("runbook_s_gauge", "x").set(7)
    h = reg.histogram("runbook_s_seconds", "x", buckets=(1.0, 2.0))
    h.observe(0.5)
    snap = json.loads(json.dumps(reg.snapshot()))
    assert snap["runbook_s_total"] == 3
    assert snap["runbook_s_gauge"] == 7
    assert snap["runbook_s_seconds"]["count"] == 1


# --------------------------------------------------------------------------- #
# tracer: warn-once disable, close, per-thread context                        #
# --------------------------------------------------------------------------- #


def test_tracer_disable_warns_once(tmp_path):
    from runbookai_tpu.utils.trace import Tracer

    tr = Tracer(tmp_path / "t.jsonl")
    with tr.span("ok"):
        pass
    tr._fh.close()  # simulate the disk/handle going away mid-flight
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        with tr.span("lost"):
            pass
        with tr.span("lost2"):
            pass
    assert tr.enabled is False
    warned = [w for w in caught if "tracing disabled" in str(w.message)]
    assert len(warned) == 1  # once, not per span


def test_tracer_close_is_silent_and_flushes(tmp_path):
    from runbookai_tpu.utils.trace import Tracer, read_spans

    tr = Tracer(tmp_path / "t.jsonl")
    with tr.span("before"):
        pass
    tr.close()
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        tr.event("after")  # deliberate close: no warning, no record
    assert not [w for w in caught if "tracing disabled" in str(w.message)]
    spans = read_spans(tmp_path / "t.jsonl")
    assert [s["name"] for s in spans] == ["before"]


def test_tracer_thread_context(tmp_path):
    from runbookai_tpu.utils.trace import Tracer, read_spans

    tr = Tracer(tmp_path / "t.jsonl")
    tr.set_context(request_id="corr-1")
    with tr.span("with-ctx"):
        tr.event("inner")
    tr.clear_context()
    with tr.span("no-ctx"):
        pass
    tr.close()
    spans = {s["name"]: s for s in read_spans(tmp_path / "t.jsonl")}
    assert spans["with-ctx"]["ctx"] == {"request_id": "corr-1"}
    assert spans["inner"]["ctx"] == {"request_id": "corr-1"}
    assert "ctx" not in spans["no-ctx"]


def test_trace_summary_and_cli(tmp_path, capsys):
    from runbookai_tpu.utils.trace import summarize_spans

    spans = [{"name": "engine.decode", "ms": float(i)} for i in range(1, 101)]
    spans += [{"name": "engine.prefill", "ms": 5.0}]
    summary = summarize_spans(spans)
    assert summary["engine.decode"]["count"] == 100
    assert summary["engine.decode"]["p50_ms"] == pytest.approx(50.5)
    assert summary["engine.decode"]["p95_ms"] == pytest.approx(95.05)
    assert summary["engine.decode"]["max_ms"] == 100.0
    assert summary["engine.prefill"]["count"] == 1

    # `runbook metrics --trace` summarizes the same JSONL from the CLI.
    path = tmp_path / "trace.jsonl"
    path.write_text("\n".join(json.dumps(s) for s in spans))
    from runbookai_tpu.cli.main import main

    rc = main(["metrics", "--trace", str(path), "--span", "decode"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert list(out) == ["engine.decode"]
    assert out["engine.decode"]["p95_ms"] == pytest.approx(95.05)


# --------------------------------------------------------------------------- #
# serving integration: /metrics, /healthz, request-id propagation             #
# --------------------------------------------------------------------------- #


@pytest.fixture(scope="module")
def server():
    from runbookai_tpu.model.jax_tpu import JaxTpuClient
    from runbookai_tpu.server.openai_api import OpenAIServer

    client = JaxTpuClient.for_testing(max_new_tokens=6)
    srv = OpenAIServer(client, model_name="llama3-test", port=0)
    srv.start_background()
    yield srv
    srv.shutdown()


def _post(srv, path, payload, headers=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{srv.port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
        method="POST")
    return urllib.request.urlopen(req, timeout=120)


def _get(srv, path):
    return urllib.request.urlopen(
        f"http://127.0.0.1:{srv.port}{path}", timeout=30)


_SAMPLE_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? "
    r"(NaN|[+-]?Inf|[-+0-9.eE]+)$")


def test_metrics_endpoint_scrapes_cleanly(server):
    with _post(server, "/v1/chat/completions", {
        "messages": [{"role": "user", "content": "hello"}],
        "max_tokens": 4,
    }) as r:
        json.loads(r.read())
    with _get(server, "/metrics") as r:
        assert r.headers["Content-Type"].startswith("text/plain")
        text = r.read().decode()
    # Acceptance names: latency histogram, KV gauge, request counter.
    assert "# TYPE runbook_ttft_seconds histogram" in text
    assert "runbook_ttft_seconds_bucket" in text
    assert "# TYPE runbook_kv_pages_in_use gauge" in text
    assert "# TYPE runbook_requests_total counter" in text
    assert 'route="/v1/chat/completions"' in text
    # The engine actually observed the request we just made.
    count_line = [ln for ln in text.splitlines()
                  if ln.startswith("runbook_ttft_seconds_count")][0]
    assert float(count_line.split()[-1]) >= 1
    # Every sample line is well-formed Prometheus text format.
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        assert _SAMPLE_LINE.match(line), line


def test_healthz_keeps_contract_and_adds_pressure(server):
    with _get(server, "/healthz") as r:
        health = json.loads(r.read())
    assert health["status"] == "ok"
    # Backward-compatible engine snapshot keys (BASELINE.md contract).
    for key in ("decode_tokens", "decode_steps", "prefill_tokens",
                "preemptions", "decode_time_s", "prefill_time_s",
                "cached_prefix_tokens", "spec_drafted", "spec_accepted"):
        assert key in health["metrics"], key
    assert health["uptime_s"] >= 0
    assert health["kv"]["pages_total"] > 0
    assert 0.0 <= health["kv"]["utilization"] <= 1.0


def test_request_id_echoed_and_generated(server):
    with _post(server, "/v1/chat/completions", {
        "messages": [{"role": "user", "content": "id"}], "max_tokens": 3,
    }, headers={"x-request-id": "corr-echo-1"}) as r:
        assert r.headers["x-request-id"] == "corr-echo-1"
        json.loads(r.read())
    # Absent header: the server generates one and echoes it.
    with _post(server, "/v1/chat/completions", {
        "messages": [{"role": "user", "content": "id2"}], "max_tokens": 3,
    }) as r:
        assert r.headers["x-request-id"].startswith("req-")
    # SSE responses carry it too (headers go out before the stream).
    with _post(server, "/v1/chat/completions", {
        "messages": [{"role": "user", "content": "s"}], "max_tokens": 3,
        "stream": True,
    }, headers={"x-request-id": "corr-sse-2"}) as r:
        assert r.headers["x-request-id"] == "corr-sse-2"
        assert r.read().decode().rstrip().endswith("[DONE]")


def test_request_id_propagates_to_trace_jsonl(tmp_path):
    """End-to-end correlation: one HTTP request's x-request-id must appear
    both in the server span's ctx and in the engine's finish event."""
    from runbookai_tpu.model.jax_tpu import JaxTpuClient
    from runbookai_tpu.server.openai_api import OpenAIServer
    from runbookai_tpu.utils.trace import Tracer, read_spans, set_tracer

    tracer = Tracer(tmp_path / "trace.jsonl")
    set_tracer(tracer)
    try:
        client = JaxTpuClient.for_testing(max_new_tokens=4)
        srv = OpenAIServer(client, model_name="llama3-test", port=0)
        srv.start_background()
        try:
            with _post(srv, "/v1/chat/completions", {
                "messages": [{"role": "user", "content": "trace me"}],
                "max_tokens": 3,
            }, headers={"x-request-id": "corr-trace-9"}) as r:
                json.loads(r.read())
        finally:
            srv.shutdown()
    finally:
        set_tracer(None)
        tracer.close()
    spans = read_spans(tmp_path / "trace.jsonl")
    server_spans = [s for s in spans if s["name"] == "server.request"
                    and s.get("ctx", {}).get("request_id") == "corr-trace-9"]
    assert server_spans, "server span missing the request id ctx"
    assert server_spans[0]["meta"]["route"] == "/v1/chat/completions"
    engine_events = [s for s in spans if s["name"] == "engine.request"
                     and s.get("meta", {}).get("trace_id") == "corr-trace-9"]
    assert engine_events, "engine finish event missing the trace id"
    assert engine_events[0]["meta"]["generated"] >= 1


# --------------------------------------------------------------------------- #
# contract: names + explicit buckets (dashboard drift caught at test time)    #
# --------------------------------------------------------------------------- #


def test_metric_name_and_bucket_contract(server):
    # Importing the instrumented layers registers their metrics; the live
    # server fixture covers the engine- and server-registered ones.
    import runbookai_tpu.agent.agent  # noqa: F401
    import runbookai_tpu.agent.parallel_executor  # noqa: F401

    metrics = list(get_registry())
    names = [m.name for m in metrics]
    # The layer is actually wired: engine, server, and agent all present.
    assert "runbook_ttft_seconds" in names
    assert "runbook_requests_total" in names
    assert "runbook_agent_tool_latency_seconds" in names
    assert "runbook_kv_pages_in_use" in names
    for m in metrics:
        assert METRIC_NAME_RE.match(m.name), m.name
        assert m.type in ("counter", "gauge", "histogram"), m.name
        if isinstance(m, Histogram):
            assert m.buckets, f"{m.name} must declare explicit buckets"
            assert list(m.buckets) == sorted(m.buckets), m.name
