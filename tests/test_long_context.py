"""Long-context serving: memory plans + the paged engine past the rope knee.

r3 VERDICT weak #7: 128k rope-scaling configs existed but nothing served
past 8k. These tests pin (a) the HBM arithmetic for 32k-128k contexts on
real chip budgets (engine/memory_plan.py), and (b) the engine actually
serving contexts beyond the Llama-3.1 rope-scaling knee (8192) through
chunked prefill + paged attention. The full 33k proof runs under
RUNBOOK_LONGCTX=1 (~3.5 min on CPU); the in-suite variant crosses the
knee at 9k.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from runbookai_tpu.engine.engine import EngineConfig, EngineCore
from runbookai_tpu.engine.memory_plan import GiB, plan_serving
from runbookai_tpu.engine.request import EngineRequest, SamplingParams
from runbookai_tpu.models.llama import CONFIGS, LlamaConfig, init_params
from runbookai_tpu.utils.tokens import ByteTokenizer


# --------------------------------------------------------------------- #
# memory plans (the numbers docs/bench quote)                           #
# --------------------------------------------------------------------- #


def test_8b_32k_fits_one_chip_with_fp8_kv():
    cfg = CONFIGS["llama3.1-8b-instruct"]
    p = plan_serving(cfg, max_seq_len=32_768, batch=1, tp=1,
                     weights="int8", kv_dtype_bytes=1)
    assert p.fits, p.explain()
    assert p.max_concurrent_contexts >= 2, p.explain()
    # KV math: 32 layers * 2 * 8 kv heads * 128 hd * 1B = 64 KiB/token.
    assert p.kv_bytes_per_token_per_chip == 32 * 2 * 8 * 128


def test_8b_128k_needs_tp():
    cfg = CONFIGS["llama3.1-8b-instruct"]
    solo = plan_serving(cfg, max_seq_len=131_072, batch=1, tp=1,
                        weights="int8", kv_dtype_bytes=1)
    assert not solo.fits, solo.explain()
    tp4 = plan_serving(cfg, max_seq_len=131_072, batch=1, tp=4,
                       weights="int8", kv_dtype_bytes=1)
    assert tp4.fits, tp4.explain()


def test_70b_128k_fits_v5e16_via_kv_split():
    cfg = CONFIGS["llama3-70b-instruct"]
    p = plan_serving(cfg, max_seq_len=131_072, batch=1, tp=16,
                     weights="int8", kv_dtype_bytes=2)
    # tp16 on 8 kv heads factors kv8 x pg2 (parallel/kv_split.py).
    assert (p.kv_shards, p.pg_shards) == (8, 2)
    assert p.fits, p.explain()
    assert p.weight_bytes_per_chip < 6 * GiB, p.explain()
    # Single chip cannot even hold the weights.
    assert plan_serving(cfg, max_seq_len=8192, tp=1,
                        weights="int8").pool_budget_bytes == 0


def test_serving_default_is_justified_by_plan():
    """The 8192 serving default: generous concurrency on one chip (the
    agent workload), while the plan shows exactly what raising it costs."""
    cfg = CONFIGS["llama3.1-8b-instruct"]
    p8k = plan_serving(cfg, max_seq_len=8192, batch=8, tp=1,
                       weights="int8", kv_dtype_bytes=1)
    assert p8k.fits and p8k.max_concurrent_contexts >= 8, p8k.explain()
    # The config ceiling for 3.1 models is the full 128k window.
    assert cfg.max_seq_len == 131_072


# --------------------------------------------------------------------- #
# engine e2e past the rope-scaling knee                                 #
# --------------------------------------------------------------------- #


def _longctx_cfg(max_seq: int) -> LlamaConfig:
    return LlamaConfig(
        name="longctx-test", vocab_size=262, dim=64, n_layers=2, n_heads=4,
        n_kv_heads=2, ffn_dim=128, max_seq_len=max_seq, rope_theta=500_000.0,
        rope_scaling=(8.0, 1.0, 4.0, 8192),  # llama3-style, knee at 8192
    )


def _serve_long(prompt_len: int, max_seq: int, new_tokens: int = 4,
                prefill_chunk: int = 1024) -> EngineRequest:
    cfg = _longctx_cfg(max_seq)
    tok = ByteTokenizer()
    params = init_params(jax.random.PRNGKey(0), cfg, dtype=jnp.float32)
    core = EngineCore(cfg, params, tok, EngineConfig(
        page_size=16, num_pages=prompt_len // 16 + 64, max_batch_slots=1,
        prefill_chunk=prefill_chunk, max_seq_len=max_seq,
        kv_dtype=jnp.float32, block_pages=64, speculative=False,
        prefill_batch=1))
    prompt = np.random.default_rng(0).integers(3, 250,
                                               size=prompt_len).tolist()
    req = EngineRequest(prompt_ids=prompt,
                        sampling=SamplingParams(temperature=0.0,
                                                max_new_tokens=new_tokens,
                                                stop_token_ids=()))
    core.submit(req)
    core.run_until_idle()
    return req


def test_engine_serves_context_past_rope_knee():
    """9k context: chunked prefill + paged decode at positions beyond the
    8192 rope-scaling knee; deterministic greedy output, all KV paged."""
    a = _serve_long(9_100, max_seq=10_240)
    assert a.finish_reason is not None
    assert a.ctx_len > 9_100  # decoded past the full prompt
    assert len(a.out_ids) == 4
    b = _serve_long(9_100, max_seq=10_240)
    assert b.out_ids == a.out_ids  # deterministic across runs


@pytest.mark.skipif(not os.environ.get("RUNBOOK_LONGCTX"),
                    reason="full 33k proof is ~3.5 min on CPU; "
                           "set RUNBOOK_LONGCTX=1")
def test_engine_serves_33k_context():
    req = _serve_long(33_000, max_seq=34_816)
    assert req.finish_reason is not None
    assert req.ctx_len > 33_000
    assert len(req.out_ids) == 4


# --------------------------------------------------------------------- #
# long context ON THE KV-SPLIT MESH (VERDICT r4 #6)                     #
# --------------------------------------------------------------------- #
#
# The 128k plans (8b tp4, 70b tp16 = kv8 x pg2) rest on the page-axis
# sequence sharding in parallel/kv_split.py. These tests run the SAME
# factorization scaled down (tp4 on n_kv=2 -> kv2 x pg2, so pg_shards>1
# exactly like the 70b plan) on the virtual 8-device CPU mesh, proving
# the plan's collectives + page math serve past the rope knee — not just
# the single-device 33k case.


def _serve_long_kv_split(prompt_len: int, max_seq: int, tp: int = 4,
                         new_tokens: int = 4,
                         prefill_chunk: int = 1024) -> EngineRequest:
    from runbookai_tpu.parallel.kv_split import plan_kv_split
    from runbookai_tpu.parallel.mesh import build_mesh
    from runbookai_tpu.parallel.sharding import param_shardings

    cfg = _longctx_cfg(max_seq)
    plan = plan_kv_split(cfg, tp)
    assert plan.pg_shards > 1, plan  # the 70b-style page split is live
    mesh = build_mesh(1, model=plan.kv_shards, seq=plan.pg_shards)
    tok = ByteTokenizer()
    params = init_params(jax.random.PRNGKey(0), cfg, dtype=jnp.float32)
    sharded = jax.tree.map(jax.device_put, params,
                           param_shardings(cfg, mesh))
    core = EngineCore(cfg, sharded, tok, EngineConfig(
        page_size=16, num_pages=prompt_len // 16 + 64, max_batch_slots=1,
        prefill_chunk=prefill_chunk, max_seq_len=max_seq,
        kv_dtype=jnp.float32, block_pages=64, speculative=False,
        prefill_batch=1), mesh=mesh)
    prompt = np.random.default_rng(0).integers(3, 250,
                                               size=prompt_len).tolist()
    req = EngineRequest(prompt_ids=prompt,
                        sampling=SamplingParams(temperature=0.0,
                                                max_new_tokens=new_tokens,
                                                stop_token_ids=()))
    core.submit(req)
    core.run_until_idle()
    return req


def test_kv_split_serves_past_rope_knee_matches_unsharded():
    """9k context on the kv2 x pg2 mesh: chunked prefill + page-split
    decode past the rope knee, greedy-identical to the single-device
    engine (the 128k plan's mechanics at test scale)."""
    ref = _serve_long(9_100, max_seq=10_240)
    got = _serve_long_kv_split(9_100, max_seq=10_240)
    assert got.finish_reason is not None
    assert got.ctx_len > 9_100
    assert got.out_ids == ref.out_ids


@pytest.mark.skipif(not os.environ.get("RUNBOOK_LONGCTX"),
                    reason="33k kv-split proof is slow on CPU; "
                           "set RUNBOOK_LONGCTX=1")
def test_kv_split_engine_serves_33k_context():
    """>32k served with pg_shards>1 (the 70b-128k factorization, scaled):
    greedy parity vs the unsharded XLA engine at the same 33k prompt."""
    ref = _serve_long(33_000, max_seq=34_816)
    got = _serve_long_kv_split(33_000, max_seq=34_816)
    assert got.finish_reason is not None
    assert got.ctx_len > 33_000
    assert got.out_ids == ref.out_ids
