"""Surface layers: demo, CLI commands, checkpoints, MCP server, webhook,
slack gateway, learning loop."""

import io
import json
import urllib.parse

import pytest

from runbookai_tpu.demo.runner import render_event, run_demo
from runbookai_tpu.session.checkpoint import CheckpointStore
from runbookai_tpu.utils.config import Config


def test_demo_script_plays_and_renders():
    events = run_demo(sleep=lambda s: None)
    kinds = [e.kind for e in events]
    assert kinds[0] == "start" and kinds[-1] == "done"
    assert kinds.count("hypothesis_created") == 4
    assert "conclusion" in kinds
    conclusion = next(e for e in events if e.kind == "conclusion")
    assert "pool" in conclusion.data["root_cause"]
    assert "┤" in conclusion.data["chart"]  # chart attached
    rendered = [render_event(e) for e in events]
    assert any("ROOT CAUSE" in r for r in rendered)
    assert any("[CONFIRM" in r.upper() or "confirm" in r for r in rendered)


def test_checkpoint_store_roundtrip(tmp_path):
    from runbookai_tpu.agent.state_machine import InvestigationStateMachine

    store = CheckpointStore(tmp_path)
    m = InvestigationStateMachine(incident_id="PD-9")
    m.add_hypothesis("h1", priority=0.7)
    meta = store.save_machine(m, label="mid")
    metas = store.list("PD-9")
    assert len(metas) == 1 and metas[0].label == "mid"
    shown = store.show(meta.checkpoint_id)
    assert shown["snapshot"]["hypothesis_detail"]["H1"]["statement"] == "h1"
    assert store.latest("PD-9")["meta"]["checkpoint_id"] == meta.checkpoint_id
    assert store.delete(meta.checkpoint_id)
    assert store.list("PD-9") == []


def test_checkpoint_prune_cap(tmp_path):
    import runbookai_tpu.session.checkpoint as cp

    store = CheckpointStore(tmp_path)
    orig = cp.MAX_CHECKPOINTS_PER_INVESTIGATION
    cp.MAX_CHECKPOINTS_PER_INVESTIGATION = 3
    try:
        for i in range(5):
            store.save("inv", {"phase": "x", "i": i})
        assert len(store.list("inv")) == 3
    finally:
        cp.MAX_CHECKPOINTS_PER_INVESTIGATION = orig


def test_cli_init_status_config(tmp_path, monkeypatch, capsys):
    from runbookai_tpu.cli.main import main

    monkeypatch.chdir(tmp_path)
    assert main(["init", "--template", "simulated"]) == 0
    assert (tmp_path / ".runbook" / "config.yaml").exists()
    assert main(["status"]) == 0
    out = capsys.readouterr().out
    assert "aws (simulated)" in out
    assert main(["config", "--set", "agent.max_iterations=4"]) == 0
    assert main(["config", "--show"]) == 0
    out = capsys.readouterr().out
    assert '"max_iterations": 4' in out


def test_cli_demo_and_eval_offline(tmp_path, monkeypatch, capsys, request):
    from runbookai_tpu.cli.main import main

    repo_fixtures = str(
        (request.config.rootpath / "examples/evals/investigation-fixtures.sample.json")
    )
    monkeypatch.setattr("time.sleep", lambda s: None)
    assert main(["demo", "--fast"]) == 0
    out = capsys.readouterr().out
    assert "ROOT CAUSE" in out
    monkeypatch.chdir(tmp_path)
    code = main(["eval", "--offline", "--fixtures", repo_fixtures,
                 "--out", str(tmp_path / "reports")])
    assert code == 0
    report = json.loads((tmp_path / "reports" / "investigation.json").read_text())
    assert report["total"] == 3 and report["passed"] == 2


def test_cli_knowledge_roundtrip(tmp_path, monkeypatch, capsys):
    from runbookai_tpu.cli.main import main

    monkeypatch.chdir(tmp_path)
    docs = tmp_path / "docs"
    docs.mkdir()
    (docs / "r.md").write_text(
        "---\ntype: runbook\nservices: [svc-a]\n---\n# Pool runbook\n\nCheck the pool.")
    cfg_dir = tmp_path / ".runbook"
    cfg_dir.mkdir()
    (cfg_dir / "config.yaml").write_text(f"""
knowledge:
  db_path: {tmp_path}/kb.db
  embedder: {{enabled: true, model: bge-test, max_length: 64}}
  sources:
    - {{type: filesystem, name: docs, path: {docs}}}
""")
    assert main(["knowledge", "sync"]) == 0
    out = capsys.readouterr().out
    assert "docs: 1 documents synced" in out
    assert main(["knowledge", "search", "pool"]) == 0
    out = capsys.readouterr().out
    assert "Pool runbook" in out
    assert main(["knowledge", "stats"]) == 0


def test_cli_ask_with_mock_runtime(tmp_path, monkeypatch, capsys):
    """`runbook ask` through build_runtime with mock provider + simulated tools."""
    from runbookai_tpu.cli.main import main

    monkeypatch.chdir(tmp_path)
    (tmp_path / ".runbook").mkdir()
    (tmp_path / ".runbook" / "config.yaml").write_text("""
llm: {provider: mock}
providers:
  aws: {enabled: true, simulated: true}
""")
    assert main(["ask", "what is on fire?", "--yes"]) == 0
    out = capsys.readouterr().out
    assert "done" in out


def test_mcp_server_protocol(tmp_path):
    from runbookai_tpu.knowledge.chunker import document_from_markdown
    from runbookai_tpu.knowledge.retriever import HybridRetriever, KnowledgeRetriever
    from runbookai_tpu.knowledge.store.sqlite_fts import KnowledgeStore
    from runbookai_tpu.server.mcp import MCPServer, run_stdio_server

    store = KnowledgeStore(":memory:")
    store.upsert_document(document_from_markdown(
        "r.md", "---\ntype: runbook\n---\n# Pool runbook\n\npool saturation steps"))
    retriever = KnowledgeRetriever(store, HybridRetriever(store))
    server = MCPServer(retriever)

    init = server.handle({"jsonrpc": "2.0", "id": 1, "method": "initialize"})
    assert init["result"]["serverInfo"]["name"] == "runbookai-tpu"
    tools = server.handle({"jsonrpc": "2.0", "id": 2, "method": "tools/list"})
    names = [t["name"] for t in tools["result"]["tools"]]
    assert "search_runbooks" in names and "get_knowledge_stats" in names
    call = server.handle({"jsonrpc": "2.0", "id": 3, "method": "tools/call",
                          "params": {"name": "search_runbooks",
                                     "arguments": {"query": "pool"}}})
    payload = json.loads(call["result"]["content"][0]["text"])
    assert payload["results"] and "Pool runbook" in payload["results"][0]["title"]
    bad = server.handle({"jsonrpc": "2.0", "id": 4, "method": "nope"})
    assert bad["error"]["code"] == -32601

    # stdio loop
    stdin = io.StringIO(json.dumps({"jsonrpc": "2.0", "id": 9,
                                    "method": "tools/list"}) + "\n")
    stdout = io.StringIO()
    run_stdio_server(server, stdin=stdin, stdout=stdout)
    reply = json.loads(stdout.getvalue())
    assert reply["id"] == 9


def test_webhook_signature_and_approval_flow(tmp_path):
    from runbookai_tpu.server.webhook import (
        ApprovalFileStore,
        verify_slack_signature,
    )
    import hashlib
    import hmac
    import time as _time

    secret = "s3cret"
    ts = str(_time.time())
    body = b"payload=%7B%7D"
    sig = "v0=" + hmac.new(secret.encode(), f"v0:{ts}:".encode() + body,
                           hashlib.sha256).hexdigest()
    assert verify_slack_signature(secret, ts, body, sig)
    assert not verify_slack_signature(secret, ts, body, "v0=bad")
    assert not verify_slack_signature(secret, "123", body, sig)  # stale ts

    store = ApprovalFileStore(tmp_path)
    store.create_pending("ap-1", {"operation": "rollback"})
    assert store.list_pending() == ["ap-1"]
    assert store.poll_response("ap-1") is None
    assert store.respond("ap-1", True, user="alice")
    resp = store.poll_response("ap-1")
    assert resp["approved"] is True and resp["user"] == "alice"
    assert store.list_pending() == []
    assert not store.respond("ap-404", True)


async def test_slack_gateway_parse_authz_dedupe():
    from runbookai_tpu.server.slack_gateway import (
        DedupeCache,
        SlackGateway,
        parse_mention_command,
    )

    assert parse_mention_command("<@U1> investigate PD-1 now") == ("investigate", "PD-1 now")
    assert parse_mention_command("<@U1> why is checkout slow") == ("infra", "why is checkout slow")
    assert parse_mention_command("<@U1>") is None

    config = Config.model_validate({
        "incident": {"slack": {"enabled": True, "allowed_channels": ["C1"],
                               "allowed_users": ["U-ok"]}}})
    answered = []

    async def run_request(req):
        answered.append(req)
        return f"answer to {req.text}"

    posts = []
    gw = SlackGateway(config=config, run_request=run_request,
                      post_message=lambda c, t, th: posts.append((c, t, th)))
    # unauthorized channel
    out = await gw.handle_event({"type": "app_mention", "channel": "C2",
                                 "user": "U-ok", "ts": "1", "text": "<@B> hi"})
    assert "Not authorized" in out
    # authorized
    out = await gw.handle_event({"type": "app_mention", "channel": "C1",
                                 "user": "U-ok", "ts": "2",
                                 "text": "<@B> infra what broke"},
                                event_id="ev1")
    assert out == "answer to what broke"
    assert posts[-1][0] == "C1"
    # dedupe: same event id ignored
    out2 = await gw.handle_event({"type": "app_mention", "channel": "C1",
                                  "user": "U-ok", "ts": "2",
                                  "text": "<@B> infra what broke"},
                                 event_id="ev1")
    assert out2 is None and len(answered) == 1
    cache = DedupeCache(ttl_s=0.0)
    assert not cache.seen("x")


async def test_learning_loop_artifacts(tmp_path):
    from runbookai_tpu.agent.orchestrator import OrchestratorResult
    from runbookai_tpu.agent.types import AgentEvent
    from runbookai_tpu.learning.loop import run_learning_loop
    from runbookai_tpu.model.client import MockLLMClient

    llm = MockLLMClient([
        "# Postmortem\n\nPool exhausted.",
        json.dumps({"suggestions": [{"type": "runbook", "title": "Pool saturation",
                                     "reason": "recurring", "services": ["payment-api"],
                                     "outline": "check pool"}]}),
    ])
    result = OrchestratorResult(
        summary={"incident_id": "PD-7"},
        root_cause="pool exhausted", confidence="high",
        affected_services=["payment-api"],
        conclusion_summary="pool too small",
        events=[AgentEvent("conclusion", {"root_cause": "pool"})],
    )
    out = await run_learning_loop(llm, result, out_dir=tmp_path)
    assert (out / "postmortem-draft.md").read_text().startswith("# Postmortem")
    suggestions = json.loads((out / "knowledge-suggestions.json").read_text())
    assert suggestions["suggestions"][0]["title"] == "Pool saturation"
    assert json.loads((out / "record.json").read_text())["root_cause"] == "pool exhausted"


# ---------------------------------------------------------------------------
# terminal UI components (reference src/cli/components/*.tsx) + setup wizard

def test_markdown_renderer_blocks():
    from runbookai_tpu.cli.markdown import parse_blocks, render_markdown

    md = """# Incident report

Root cause was a **bad deploy** touching `payments`.

- first item
- second item

```bash
kubectl rollout undo deploy/payments
```

| svc | status |
|-----|--------|
| payments | degraded |

> quote line
"""
    kinds = [b.kind for b in parse_blocks(md)]
    assert kinds == ["header", "paragraph", "list", "code", "table", "blockquote"]

    plain = render_markdown(md, color=False)
    assert "# Incident report" in plain
    assert "bad deploy" in plain and "**" not in plain
    assert "• first item" in plain
    assert "kubectl rollout undo" in plain
    assert "│ payments" in plain

    ansi = render_markdown(md, color=True)
    assert "\x1b[1m" in ansi  # bold somewhere


def test_markdown_ordered_list_and_links():
    from runbookai_tpu.cli.markdown import render_markdown

    md = "1. step one\n2. step two\n\nsee [runbook](https://kb/x)"
    plain = render_markdown(md, color=False)
    assert "1. step one" in plain and "2. step two" in plain
    assert "runbook <https://kb/x>" in plain


def test_hypothesis_tree_rendering():
    from runbookai_tpu.agent.state_machine import FSMHypothesis
    from runbookai_tpu.cli.hypothesis_view import (
        count_statuses,
        render_summary,
        render_tree,
    )

    nodes = [
        FSMHypothesis(id="h1", statement="bad deploy", status="confirmed",
                      confidence=85.0, children=["h2", "h3"]),
        FSMHypothesis(id="h2", statement="config drift", parent_id="h1",
                      status="pruned", depth=1),
        FSMHypothesis(id="h3", statement="pool exhaustion", parent_id="h1",
                      status="investigating", depth=1,
                      evidence=[{"summary": "x"}]),
    ]
    tree = render_tree(nodes, color=False)
    assert "● bad deploy 85%" in tree
    assert "├─" in tree and "└─" in tree
    assert "config drift" in tree
    hidden = render_tree(nodes, show_pruned=False, color=False)
    assert "config drift" not in hidden
    assert "[1 evidence]" in tree

    counts = count_statuses(nodes)
    assert counts["confirmed"] == 1 and counts["pruned"] == 1
    summary = render_summary(nodes, color=False)
    assert "Root cause: bad deploy (85%)" in summary


def test_wizard_scripted_flow_and_save(tmp_path):
    from runbookai_tpu.cli.wizard import (
        OnboardingAnswers,
        generate_configs,
        hydrate_answers,
        run_wizard,
        save_wizard_configs,
    )

    answers_script = iter([
        "custom",            # template
        "jax-tpu", "llama3-8b-instruct",
        "multi", "prod,staging", "us-east-1,eu-west-1",
        "ecs,eks", "rds",
        "y",                  # kubernetes
        "pagerduty",
        "n",                  # slack
        "./docs/runbooks",
    ])
    answers = run_wizard(ask=lambda q, d: next(answers_script))
    assert answers.account_names == ["prod", "staging"]
    assert answers.compute_services == ["ecs", "eks"]
    assert answers.use_kubernetes

    config_path, services_path = save_wizard_configs(answers, tmp_path)
    assert config_path.exists() and services_path.exists()

    config, services = generate_configs(answers)
    assert config.llm.provider == "jax-tpu"
    assert config.providers.kubernetes.enabled  # eks implies k8s
    assert config.incident.pagerduty.enabled
    assert len(services.accounts) == 2
    assert {s.type for s in services.services} == {"ecs", "eks", "rds"}

    # hydration round-trip picks the saved answers back up
    hydrated = hydrate_answers(tmp_path)
    assert hydrated.account_setup == "multi"
    assert hydrated.compute_services == ["ecs", "eks"]
    assert hydrated.incident_provider == "pagerduty"


def test_wizard_quick_template():
    from runbookai_tpu.cli.wizard import run_wizard

    answers = run_wizard(ask=lambda q, d: "kubernetes")
    assert answers.use_kubernetes and answers.compute_services == ["eks"]


def test_markdown_unterminated_table_does_not_hang():
    from runbookai_tpu.cli.markdown import parse_blocks, render_markdown

    blocks = parse_blocks("| a | b")  # no trailing pipe — must still terminate
    assert [b.kind for b in blocks] == ["table"]
    assert "a" in render_markdown("| a | b\nplain text after", color=False)


def test_hypothesis_confidence_fraction_scaling():
    from runbookai_tpu.agent.state_machine import FSMHypothesis
    from runbookai_tpu.cli.hypothesis_view import render_summary, render_tree

    nodes = [FSMHypothesis(id="h", statement="bad deploy",
                           status="confirmed", confidence=0.85)]
    assert "85%" in render_tree(nodes, color=False)
    assert "(85%)" in render_summary(nodes, color=False)


def test_cli_chat_raw_streams(tmp_path, monkeypatch, capsys):
    """chat --raw streams through the LLMClient event protocol (mock
    fallback here; true token streaming on the jax-tpu provider)."""
    from runbookai_tpu.cli.main import main

    monkeypatch.chdir(tmp_path)
    inputs = iter(["hello there", ""])
    monkeypatch.setattr("builtins.input", lambda *a: next(inputs))
    assert main(["chat", "--raw"]) == 0
    out = capsys.readouterr().out
    assert "streaming model chat" in out


def test_cli_serve_requires_engine_provider(tmp_path, monkeypatch, capsys):
    from runbookai_tpu.cli.main import main

    monkeypatch.chdir(tmp_path)
    # Default config is the mock provider: serve must refuse, not crash.
    assert main(["serve", "--port", "0"]) == 1


def test_llm_config_knobs():
    from runbookai_tpu.models.llama import CONFIGS
    from runbookai_tpu.utils.config import LLMConfig

    assert CONFIGS["qwen2.5-7b-instruct"].family == "qwen2"
    assert LLMConfig().attn_impl == "auto"
    assert LLMConfig(attn_impl="xla").attn_impl == "xla"


def test_live_tree_sink_repaints_during_run():
    """TTY mode: the hypothesis tree erases + repaints under the event
    stream (reference Ink live tree); non-TTY falls back to line events."""
    import io

    from runbookai_tpu.agent.state_machine import InvestigationStateMachine
    from runbookai_tpu.agent.types import AgentEvent
    from runbookai_tpu.cli.live_view import LiveTreeSink

    machine = InvestigationStateMachine(incident_id="INC-9")
    out = io.StringIO()
    lines: list = []
    sink = LiveTreeSink(machine, fallback=lambda ev: lines.append(ev.kind),
                        out=out, enabled=True)

    sink(AgentEvent("phase_change", {"phase": "triage"}))
    assert "\x1b[" not in out.getvalue()  # nothing painted yet (no hyps)

    machine.add_hypothesis("db pool exhausted", priority=8)
    sink(AgentEvent("hypothesis_created", {"id": "H1"}))
    first = out.getvalue()
    assert "db pool exhausted" in first

    machine.add_hypothesis("bad deploy", priority=5)
    sink(AgentEvent("hypothesis_created", {"id": "H2"}))
    second = out.getvalue()[len(first):]
    # The repaint erased the old block (cursor-up F + clear 0J) and the
    # new tree carries BOTH hypotheses.
    assert "\x1b[" in second and "F\x1b[0J" in second
    assert "bad deploy" in second and "db pool exhausted" in second
    assert lines == ["phase_change", "hypothesis_created",
                     "hypothesis_created"]

    # Non-TTY: pure passthrough, zero ANSI.
    out2 = io.StringIO()
    plain: list = []
    sink2 = LiveTreeSink(machine, fallback=lambda ev: plain.append(ev.kind),
                         out=out2, enabled=False)
    sink2(AgentEvent("hypothesis_created", {"id": "H3"}))
    assert out2.getvalue() == "" and plain == ["hypothesis_created"]
